#!/usr/bin/env python
"""psmon — live cluster-wide telemetry monitor (docs/observability.md).

Asks the scheduler for a ``METRICS_PULL`` snapshot of every node's
metrics registry and renders one table row per node (request-latency
quantiles, lane depth, apply-shard throughput, retransmits, replication
forwards/lag) plus per-role rollups and each server's hottest keys.

On top of the one-shot table sit the CONTINUOUS modes, backed by the
scheduler's :class:`~pslite_tpu.telemetry.ClusterHistory` sampler:

- ``--watch``: a live refreshing table with **windowed** rates (counter
  deltas over the sampling window, not uptime averages), sparkline
  trend columns, and a health-event footer from the SLO watchdog.
- ``--serve PORT``: an OpenMetrics/Prometheus text endpoint over
  ``http.server`` — counters, gauges, and the log2 histogram buckets
  mapped to cumulative ``le`` buckets, so any standard scraper attaches
  to any cluster.

Library use (in-process clusters, tests, notebooks)::

    from tools import psmon
    snap = psmon.collect(scheduler_postoffice)   # {node_id: snapshot}
    print(psmon.format_table(snap))              # or psmon.to_json(snap)
    hist = scheduler_postoffice.start_history(interval_s=1.0)
    print(psmon.format_watch(hist))              # windowed rates + health
    print(psmon.to_prometheus(snap))             # exposition text

CLI: ``python tools/psmon.py [--json|--watch|--serve PORT]`` boots a
live demo LoopbackCluster (2 workers, 2 servers, scheduler), drives a
short push/pull storm, and renders through the chosen mode — the
end-to-end proof of the pull plane without an external deployment.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional

# Script use from anywhere: put the repo root ahead of tools/.
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def collect(scheduler_po, timeout_s: float = 5.0) -> Dict[int, dict]:
    """Cluster snapshot via the scheduler's METRICS_PULL broadcast:
    ``{node_id: telemetry_snapshot}`` (nodes that failed to answer
    within the timeout are absent — pair with
    :func:`stale_ages` / ``format_table(..., stale=...)`` to render
    them as last-seen ages instead of silently dropping the row)."""
    return scheduler_po.collect_cluster_metrics(timeout_s=timeout_s)


def stale_ages(scheduler_po, snap: Dict[int, dict]) -> Dict[int, float]:
    """``{node_id: seconds since last METRICS_PULL reply}`` for every
    node the scheduler has EVER heard from that is missing from
    ``snap`` (it was asked and did not answer in time)."""
    now = time.time()
    return {
        nid: round(now - t, 3)
        for nid, t in scheduler_po.metrics_last_seen().items()
        if nid not in snap
    }


def to_json(snap: Dict[int, dict]) -> str:
    return json.dumps({str(k): v for k, v in sorted(snap.items())},
                      indent=2, sort_keys=True)


def _c(m: dict, name: str) -> int:
    return int(m.get("counters", {}).get(name, 0))


def _g(m: dict, name: str) -> float:
    return float(m.get("gauges", {}).get(name, 0.0))


def _req_quantiles(m: dict) -> tuple:
    """Merged push/pull request-latency (p50, p99) in ms — worker side.

    TRUE merged quantiles: both histogram snapshots carry their raw
    log2 ``buckets``, so the two populations merge exactly (same
    bucket geometry) instead of the old "busier path wins"
    approximation that hid a slow-but-quieter path entirely."""
    from pslite_tpu.telemetry.metrics import (bucket_quantile,
                                              merge_bucket_lists)

    hp = m.get("histograms", {}).get("kv.push_latency_s") or {}
    hl = m.get("histograms", {}).get("kv.pull_latency_s") or {}
    lo_p, lo_l = hp.get("lo", 1e-6), hl.get("lo", 1e-6)
    if hp and hl and abs(lo_p - lo_l) > 1e-18:
        # Different bucket geometry cannot merge exactly — fall back
        # to the busier path (never happens for the stock histograms).
        busy = hp if hp.get("count", 0) >= hl.get("count", 0) else hl
        return busy.get("p50", 0.0) * 1e3, busy.get("p99", 0.0) * 1e3
    merged = merge_bucket_lists(hp.get("buckets"), hl.get("buckets"))
    if not merged:
        return 0.0, 0.0
    mins = [h["min"] for h in (hp, hl) if h.get("count", 0) > 0]
    maxs = [h["max"] for h in (hp, hl) if h.get("count", 0) > 0]
    clamp_lo = min(mins) if mins else None
    clamp_hi = max(maxs) if maxs else None
    return (
        bucket_quantile(merged, lo_p, 0.5, clamp_lo, clamp_hi) * 1e3,
        bucket_quantile(merged, lo_p, 0.99, clamp_lo, clamp_hi) * 1e3,
    )


def _fmt_bytes(v: float) -> str:
    """Compact byte count for fixed-width columns (999, 12K, 3.4M, 2G)."""
    v = float(v)
    for div, suffix in ((1 << 30, "G"), (1 << 20, "M"), (1 << 10, "K")):
        if v >= div:
            q = v / div
            return f"{q:.1f}{suffix}" if q < 10 else f"{q:.0f}{suffix}"
    return f"{v:.0f}"


def _tier_cells(m: dict) -> tuple:
    """(ram/cold bytes, cold-hit-rate) cells of the tiered store
    (docs/durability.md): '-' on nodes without a TieredStore (the
    gauges only exist under PS_STORE_RAM_MB) or with PS_TELEMETRY=0."""
    gauges = m.get("gauges", {})
    if ("kv.tier_ram_bytes" not in gauges
            and "kv.tier_cold_bytes" not in gauges):
        return f"{'-':>13}", f"{'-':>6}"
    tier = (f"{_fmt_bytes(_g(m, 'kv.tier_ram_bytes'))}/"
            f"{_fmt_bytes(_g(m, 'kv.tier_cold_bytes'))}")
    gets = _c(m, "kv.tier_gets")
    cold = _c(m, "kv.cold_hits")
    rate = (f"{100.0 * cold / gets:>5.1f}%" if gets > 0
            else f"{'-':>6}")
    return f"{tier:>13}", rate


def _apply_row(m: dict, uptime: float) -> tuple:
    n = _c(m, "apply.sharded_requests") + _c(m, "apply.global_requests")
    rate = n / uptime if uptime > 0 else 0.0
    depth = sum(
        v for k, v in m.get("gauges", {}).items()
        if k.startswith("apply.shard") and k.endswith(".depth")
    )
    return n, rate, depth


def format_table(snap: Dict[int, dict], top_keys: int = 3,
                 stale: Optional[Dict[int, float]] = None,
                 health: Optional[list] = None) -> str:
    """Human-readable per-node table + per-role and per-tenant
    rollups (docs/qos.md).  ``stale`` ({node_id: last-seen age s})
    renders nodes that missed the pull as aged rows instead of
    dropping them; ``health`` (HealthEvent list) appends the
    watchdog footer."""
    # ``epoch`` (elastic membership), ``ops/F`` (small-op batching),
    # the tiered-store cells (``ram/cold`` bytes + cold-hit-rate —
    # docs/durability.md), and ``read%`` (each server's share of the
    # cluster's served pulls — docs/serving_reads.md; with replica
    # reads on, a healthy spread reads near-even across a chain, and
    # 100% on one rank is the primary funnel) ride LAST, in landing
    # order: existing consumers parse earlier columns by index.
    hdr = (f"{'node':>5} {'role':>9} {'up_s':>7} {'req_p50ms':>9} "
           f"{'req_p99ms':>9} {'lane_q':>6} {'xfers':>6} {'apply_n':>8} "
           f"{'apply/s':>8} {'retx':>6} {'repl_fwd':>8} {'repl_lag':>8} "
           f"{'cmpr':>6} {'cache%':>6} {'sent':>7} {'recv':>7} "
           f"{'epoch':>5} {'ops/F':>6} {'resp ops/F':>10} "
           f"{'ram/cold':>13} {'cold%':>6} {'read%':>6}")
    total_pulls = sum(
        _c(s.get("metrics", {}), "kv.server_pull_requests")
        for s in snap.values()
    )
    lines = [hdr, "-" * len(hdr)]
    rollup: Dict[str, Dict[str, float]] = {}
    # Elastic membership (docs/elasticity.md): per-node routing epoch
    # and, for servers, the key ranges they own under it.
    membership_lines: List[str] = []
    # Per-tenant request/shed totals across the cluster (the server-
    # side ``tenant.<name>.requests`` / ``.shed`` counters).
    tenants: Dict[str, Dict[str, int]] = {}
    hot_lines: List[str] = []
    warn_lines: List[str] = []
    for node_id in sorted(snap):
        s = snap[node_id]
        m = s.get("metrics", {})
        uptime = float(m.get("uptime_s", 0.0))
        p50, p99 = _req_quantiles(m)
        apply_n, apply_rate, _apply_depth = _apply_row(m, uptime)
        lane_q = _g(m, "van.lane_depth")
        # In-flight chunked transfers (partially reassembled) on this
        # node — docs/chunking.md; a persistently nonzero value with
        # idle traffic means leaked reassembly state.
        xfers = _g(m, "van.xfers_inflight")
        retx = _c(m, "resender.retransmits")
        fwd = _c(m, "replication.forwards")
        lag = _g(m, "replication.lag")
        sent = _c(m, "van.sent_messages")
        recv = _c(m, "van.recv_messages")
        # Wire-compression ratio this node ENCODED at (codec tier,
        # docs/compression.md): raw payload bytes / wire bytes.  "-"
        # when the node encoded nothing (or PS_TELEMETRY=0).
        craw = _c(m, "codec.raw_bytes")
        cwire = _c(m, "codec.wire_bytes")
        cmpr = f"{craw / cwire:>6.1f}" if cwire > 0 else f"{'-':>6}"
        # Hot-key cache hit rate (kv/hot_cache.py): worker-side; "-"
        # when the node never consulted a cache (PS_HOT_CACHE off).
        hits = _c(m, "kv.hot_cache.hits")
        misses = _c(m, "kv.hot_cache.misses")
        cache = (f"{100.0 * hits / (hits + misses):>5.1f}%"
                 if hits + misses > 0 else f"{'-':>6}")
        role = s.get("role", "?")
        routing = s.get("routing") or {}
        epoch = (f"{routing['epoch']:>5}" if "epoch" in routing
                 else f"{'-':>5}")
        # Small-op aggregation depth this node SENT at (docs/
        # batching.md): sub-ops per multi-op frame, split by
        # direction — request frames (worker op combiner) and
        # response frames (server batched group responses + response
        # combiner, the serving fan-in plane).  "-" when the node
        # never emitted an EXT_BATCH frame in that direction
        # (combiner off, nothing coalesced, or PS_TELEMETRY=0).
        bframes = _c(m, "van.batched_frames")
        bops = _c(m, "van.batch_ops")
        opsf = (f"{bops / bframes:>6.1f}" if bframes > 0 else f"{'-':>6}")
        rframes = _c(m, "van.resp_batched_frames")
        rops = _c(m, "van.resp_batch_ops")
        ropsf = (f"{rops / rframes:>10.1f}" if rframes > 0
                 else f"{'-':>10}")
        tier, coldp = _tier_cells(m)
        # Read share (docs/serving_reads.md): this node's slice of all
        # served pulls cluster-wide.  "-" on non-servers or before the
        # first pull.
        served = _c(m, "kv.server_pull_requests")
        readp = (f"{100.0 * served / total_pulls:>5.1f}%"
                 if served > 0 and total_pulls > 0 else f"{'-':>6}")
        lines.append(
            f"{node_id:>5} {role:>9} {uptime:>7.1f} {p50:>9.3f} "
            f"{p99:>9.3f} {lane_q:>6.0f} {xfers:>6.0f} {apply_n:>8} "
            f"{apply_rate:>8.1f} {retx:>6} {fwd:>8} {lag:>8.0f} "
            f"{cmpr} {cache} {sent:>7} {recv:>7} {epoch} {opsf} {ropsf} "
            f"{tier} {coldp} {readp}"
        )
        # Silent span loss made loud (docs/observability.md): a
        # nonzero trace.dropped_events means this node's exported
        # Chrome trace is INCOMPLETE — say so instead of letting a
        # truncated trace masquerade as a quiet one.
        dropped = _c(m, "trace.dropped_events")
        if dropped > 0:
            warn_lines.append(
                f"  WARNING node {node_id} ({role}): tracer dropped "
                f"{dropped} span(s) — its trace export is incomplete "
                f"(raise Tracer.MAX_EVENTS or lower PS_TRACE_SAMPLE)"
            )
        if routing:
            owned = routing.get("owned")
            if owned is not None:
                pretty = (", ".join(f"[{b:#x}, {e:#x})" for b, e in owned)
                          or "(none)")
                membership_lines.append(
                    f"  node {node_id} ({role}) epoch "
                    f"{routing.get('epoch')}: owns {pretty}"
                )
            elif role == "scheduler":
                membership_lines.append(
                    f"  active ranks: {routing.get('active')}  leaving: "
                    f"{routing.get('leaving')}  (epoch "
                    f"{routing.get('epoch')})"
                )
        # Published model namespace (docs/serving_reads.md): which
        # immutable model version this server is flipped to — the
        # cluster-wide A/B answer at a glance.
        ns = s.get("namespace")
        if ns:
            membership_lines.append(
                f"  node {node_id} ({role}) serving namespace "
                f"{ns.get('name')!r} version {ns.get('version')!r}"
            )
        for cname, cval in m.get("counters", {}).items():
            # tenant.<name>.<kind> — names are identifier-like (the
            # PS_TENANTS parser rejects dots), but rsplit keeps this
            # robust to any counter shape regardless.
            if cname.startswith("tenant.") and cname.count(".") >= 2:
                tname, kind = cname[len("tenant."):].rsplit(".", 1)
                t = tenants.setdefault(tname, {"requests": 0, "shed": 0})
                if kind in t:
                    t[kind] += int(cval)
        r = rollup.setdefault(role, {"nodes": 0, "sent": 0, "recv": 0,
                                     "apply": 0, "retx": 0, "fwd": 0})
        r["nodes"] += 1
        r["sent"] += sent
        r["recv"] += recv
        r["apply"] += apply_n
        r["retx"] += retx
        r["fwd"] += fwd
        top = m.get("topk", {}).get("kv.hot_keys") or []
        if top:
            pretty = ", ".join(f"{k}:{n}" for k, n in top[:top_keys])
            hot_lines.append(f"  node {node_id} ({role}) hot keys: {pretty}")
    # Nodes that were asked but never answered: a STALE row with the
    # last-seen age — an absent node is a finding, not a blank.
    for node_id in sorted(stale or {}):
        if node_id in snap:
            continue
        lines.append(
            f"{node_id:>5} {'STALE':>9}  no METRICS_PULL reply — last "
            f"seen {stale[node_id]:.1f}s ago"
        )
    if warn_lines:
        lines.append("")
        lines.extend(warn_lines)
    lines.append("")
    lines.append("per-role rollup:")
    for role in sorted(rollup):
        r = rollup[role]
        lines.append(
            f"  {role:>9}: {int(r['nodes'])} node(s), "
            f"sent={int(r['sent'])} recv={int(r['recv'])} "
            f"apply={int(r['apply'])} retx={int(r['retx'])} "
            f"repl_fwd={int(r['fwd'])}"
        )
    if tenants:
        lines.append("")
        lines.append("per-tenant rollup (docs/qos.md):")
        for tname in sorted(tenants):
            t = tenants[tname]
            total = t["requests"]
            shed_pct = 100.0 * t["shed"] / total if total else 0.0
            lines.append(
                f"  {tname:>9}: requests={total} shed={t['shed']} "
                f"({shed_pct:.1f}%)"
            )
    if membership_lines:
        lines.append("")
        lines.append("elastic membership (docs/elasticity.md):")
        lines.extend(membership_lines)
    if hot_lines:
        lines.append("")
        lines.extend(hot_lines)
    if health:
        lines.append("")
        lines.append("health events (SLO watchdog, docs/observability.md):")
        lines.extend(_health_lines(health))
    return "\n".join(lines)


# -- wire-plane table (docs/observability.md) --------------------------------


def _hist(m: dict, name: str) -> dict:
    return m.get("histograms", {}).get(name) or {}


def format_wire(snap: Dict[int, dict]) -> str:
    """Per-(node, plane) wire-plane table: syscalls/op, frames/op,
    combiner batch fill, lane-queue residency p99, and the zero-copy
    byte share.  One row per plane that actually carried traffic —
    the Python shards (``wire.*``) and the native core's counter
    block (``wire.native.*``) are judged side by side, so a regressed
    fallback path can't hide behind a healthy native plane."""
    header = (f"{'node':>5} {'role':>9} {'plane':>6} {'ops':>9} "
              f"{'sys/op':>7} {'frm/op':>7} {'fill':>6} "
              f"{'resid p99':>10} {'zc%':>6} {'bytes':>8}")
    lines = [header, "-" * len(header)]

    def ratio(num: float, den: float, w: int = 7) -> str:
        return f"{num / den:>{w}.2f}" if den > 0 else f"{'-':>{w}}"

    for node_id in sorted(snap):
        s = snap[node_id]
        m = s.get("metrics", {})
        role = s.get("role", "?")
        planes = []
        py_ops = _c(m, "wire.tx.ops") + _c(m, "wire.rx.ops")
        py_sys = _c(m, "wire.tx.syscalls") + _c(m, "wire.rx.syscalls")
        py_frm = _c(m, "wire.tx.frames") + _c(m, "wire.rx.frames")
        py_zc = _c(m, "wire.tx.bytes_zc") + _c(m, "wire.rx.bytes_zc")
        py_cp = _c(m, "wire.tx.bytes_copy") + _c(m, "wire.rx.bytes_copy")
        if py_ops or py_frm:
            planes.append(("py", py_ops, py_sys, py_frm, py_zc, py_cp))
        nt_ops = _c(m, "wire.native.tx.ops")
        nt_sys = (_c(m, "wire.native.tx.syscalls")
                  + _c(m, "wire.native.rx.syscalls"))
        nt_frm = (_c(m, "wire.native.tx.frames")
                  + _c(m, "wire.native.rx.frames"))
        nt_zc = (_c(m, "wire.native.tx.bytes_zc")
                 + _c(m, "wire.native.rx.bytes_zc"))
        nt_cp = _c(m, "wire.native.rx.bytes_copy")
        if nt_ops or nt_frm:
            planes.append(("native", nt_ops, nt_sys, nt_frm, nt_zc, nt_cp))
        occ = _hist(m, "wire.batch_occupancy")
        fill = (f"{occ['sum'] / occ['count']:>6.2f}"
                if occ.get("count") else f"{'-':>6}")
        res = _hist(m, "wire.lane_residency_s")
        resid = (f"{res.get('p99', 0.0) * 1e3:>8.2f}ms"
                 if res.get("count") else f"{'-':>10}")
        for plane, ops, sys_n, frm, zc, cp in planes:
            tot = zc + cp
            zc_pct = f"{100.0 * zc / tot:>5.1f}%" if tot else f"{'-':>6}"
            lines.append(
                f"{node_id:>5} {role:>9} {plane:>6} {ops:>9} "
                f"{ratio(sys_n, ops)} {ratio(frm, ops)} {fill} "
                f"{resid} {zc_pct} {_fmt_bytes(tot):>8}"
            )
        if not planes:
            lines.append(f"{node_id:>5} {role:>9} {'-':>6} {'-':>9} "
                         f"{'-':>7} {'-':>7} {fill} {resid} "
                         f"{'-':>6} {'-':>8}")
    rec = sum(_c(snap[n].get("metrics", {}), "wire.telemetry.records")
              for n in snap)
    fl = sum(_c(snap[n].get("metrics", {}), "wire.telemetry.flushes")
             for n in snap)
    lines.append("")
    lines.append(f"telemetry self-accounting: {rec} records in {fl} "
                 f"flushes ({rec / fl:.0f} records/flush)" if fl
                 else "telemetry self-accounting: wire plane dark "
                      "(PS_WIRE_TELEMETRY=0 or no traffic)")
    return "\n".join(lines)


# -- live watch (windowed rates + sparklines + health footer) ----------------


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(series: List[Optional[float]], width: int = 10) -> str:
    """Unicode mini-chart of one per-sample rate series (None → '·')."""
    series = list(series)[-width:]
    if len(series) < width:
        series = [None] * (width - len(series)) + series
    vals = [v for v in series if v is not None]
    if not vals:
        return "·" * width
    lo, hi = min(vals), max(vals)
    span = hi - lo
    out = []
    for v in series:
        if v is None:
            out.append("·")
        elif span <= 0:
            out.append(_SPARK[3])
        else:
            out.append(_SPARK[min(7, int((v - lo) / span * 7.999))])
    return "".join(out)


def _health_lines(events, limit: int = 8) -> List[str]:
    out = []
    for ev in list(events)[-limit:]:
        who = f"node {ev.node_id} ({ev.role})"
        if ev.tenant:
            who += f" tenant {ev.tenant}"
        out.append(
            f"  [{ev.severity.upper():>4}] "
            f"{time.strftime('%H:%M:%S', time.localtime(ev.wall))} "
            f"{ev.rule}: {who} — {ev.message}"
        )
    return out or ["  (none)"]


def format_watch(history, top_keys: int = 3, traces=None) -> str:
    """One ``--watch`` frame from the scheduler's ClusterHistory:
    per-node WINDOWED rates (counter deltas over the sampling window —
    meaningful an hour into a run, unlike uptime averages), sparkline
    trends, stale-node ages, and the watchdog footer.  ``traces`` (a
    ``telemetry.TraceCollector`` — the scheduler's, kept warm by
    ``collect_cluster_traces``) appends the tail critical-path footer:
    which pipeline stage the assembled slow traces spend their wall
    time in (tools/pstrace.py has the full view)."""
    window = history.default_window_s
    hdr = (f"{'node':>5} {'role':>9} {'req_p50ms':>9} {'req_p99ms':>9} "
           f"{'in/s':>8} {'out/s':>8} {'apply/s':>8} {'shed/s':>7} "
           f"{'retx/s':>7} {'lane_q':>6} {'repl_lag':>8} "
           f"{'trend(out/s)':>12}")
    lines = [
        f"psmon --watch  interval={history.interval_s:g}s "
        f"window={window:.1f}s samples={history.samples}",
        hdr, "-" * len(hdr),
    ]
    stale = history.stale_ages()
    for node_id in history.node_ids():
        role = history.role_of(node_id)
        m = history.latest(node_id) or {}
        p50 = history.window_quantile(
            node_id, ["kv.push_latency_s", "kv.pull_latency_s"], 0.5)
        p99 = history.window_quantile(
            node_id, ["kv.push_latency_s", "kv.pull_latency_s"], 0.99)
        rate = lambda c: history.rate(node_id, c)  # noqa: E731

        def fmt_r(v, w=8):
            return f"{v:>{w}.1f}" if v is not None else f"{'-':>{w}}"

        def fmt_ms(v, w=9):
            return f"{v * 1e3:>{w}.3f}" if v is not None else f"{'-':>{w}}"

        apply_rate = None
        a_sh = rate("apply.sharded_requests")
        a_gl = rate("apply.global_requests")
        if a_sh is not None or a_gl is not None:
            apply_rate = (a_sh or 0.0) + (a_gl or 0.0)
        row = (
            f"{node_id:>5} {role:>9} {fmt_ms(p50)} {fmt_ms(p99)} "
            f"{fmt_r(rate('van.recv_messages'))} "
            f"{fmt_r(rate('van.sent_messages'))} "
            f"{fmt_r(apply_rate)} "
            f"{fmt_r(rate('qos.shed_requests'), 7)} "
            f"{fmt_r(rate('resender.retransmits'), 7)} "
            f"{_g(m, 'van.lane_depth'):>6.0f} "
            f"{_g(m, 'replication.lag'):>8.0f} "
            f"{_sparkline(history.trend(node_id, 'van.sent_messages')):>12}"
        )
        if node_id in stale:
            row += f"  STALE {stale[node_id]:.1f}s"
        lines.append(row)
        dropped = _c(m, "trace.dropped_events")
        if dropped > 0:
            lines.append(f"      ^ WARNING: tracer dropped {dropped} "
                         f"span(s) — trace export incomplete")
    # Snapshot age (docs/durability.md): the durable-tier freshness
    # line.  Only servers configured with PS_SNAPSHOT_DIR export the
    # gauge; a negative age means the directory holds no committed
    # manifest yet.
    snap_ages = []
    for node_id in history.node_ids():
        m = history.latest(node_id) or {}
        age = m.get("gauges", {}).get("snapshot.age_s")
        if age is not None:
            snap_ages.append(float(age))
    if snap_ages:
        committed = [a for a in snap_ages if a >= 0]
        lines.append("")
        if committed:
            lines.append(
                f"snapshot age: {min(committed):.0f}s newest / "
                f"{max(committed):.0f}s oldest across "
                f"{len(snap_ages)} server(s)"
            )
        else:
            lines.append(
                f"snapshot age: no committed manifest yet "
                f"({len(snap_ages)} server(s) configured)"
            )
    changes = history.membership_log()
    if changes:
        lines.append("")
        lines.append("membership/epoch changes:")
        for ch in changes[-5:]:
            when = time.strftime("%H:%M:%S", time.localtime(ch["wall"]))
            if ch["change"] == "epoch":
                lines.append(f"  {when} epoch {ch['epoch']}: active="
                             f"{ch.get('active')} leaving="
                             f"{ch.get('leaving')}")
            else:
                lines.append(f"  {when} {ch['change']}: node "
                             f"{ch.get('node_id')} ({ch.get('role')})")
    lines.append("")
    lines.append("health (SLO watchdog):")
    lines.extend(_health_lines(history.watchdog.events(min_severity="info")))
    lines.extend(_autopilot_lines(history))
    if traces is not None:
        agg = traces.aggregate()
        lines.append("")
        if agg["count"]:
            shares = agg["slow"]
            top = sorted(shares.items(),
                         key=lambda kv: -kv[1]["total_us"])[:4]
            pretty = " | ".join(
                f"{name} {info['share'] * 100:.0f}%"
                for name, info in top if info["total_us"] > 0
            )
            lines.append(
                f"critical path ({agg['count']} tail traces, slowest "
                f"{agg['slow_count']}): {pretty}  "
                f"[pstrace --slowest for detail]"
            )
        else:
            lines.append("critical path: no assembled tail traces "
                         "(PS_TRACE_TAIL off, or nothing kept)")
    return "\n".join(lines)


def _autopilot_lines(history, last: int = 5) -> list:
    """The autopilot decision footer (docs/autopilot.md): mode, outcome
    tallies, and the last few decisions with rule/action/outcome — the
    loop's narration, inline where the operator already looks."""
    ap = getattr(history, "autopilot", None)
    if ap is None:
        return []
    counts = ap.counts()
    tally = " ".join(f"{counts.get(k, 0)} {k}" for k in
                     ("acted", "planned", "vetoed", "failed"))
    lines = ["", f"autopilot ({ap.mode}): {tally}"]
    now = time.time()
    for d in ap.decisions(last):
        age = max(0.0, now - d.wall)
        extra = d.detail.get("veto") or d.detail.get("error") or d.reason
        lines.append(f"  {age:6.1f}s ago  {d.rule:<13} "
                     f"{d.action:<13} {d.outcome:<8} {extra}")
    if not ap.decision_log:
        lines.append("  (no decisions yet)")
    return lines


# -- OpenMetrics / Prometheus exposition -------------------------------------


PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_TENANT_RE = re.compile(r"^tenant\.(?P<tenant>.+)\.(?P<kind>[^.]+)$")


def _prom_name(name: str) -> str:
    return "pslite_" + _NAME_RE.sub("_", name)


def _prom_float(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    return repr(float(v))


def to_prometheus(snap: Dict[int, dict],
                  openmetrics: bool = False) -> str:
    """Render a cluster snapshot as Prometheus text exposition
    (version 0.0.4 by default — what ``--serve`` answers plain
    scrapes with).

    - counters → ``pslite_<name>_total`` (per-tenant counters become
      one family with a ``tenant`` label),
    - gauges → ``pslite_<name>``,
    - histograms → cumulative ``_bucket{le=...}`` series derived from
      the raw log2 buckets (upper bound ``lo * 2^i``; monotone le and
      monotone cumulative counts by construction), plus ``_sum`` and
      ``_count``.

    ``openmetrics=True`` switches to OpenMetrics 1.0 output (what
    ``--serve`` answers when the scraper's Accept header asks for
    ``application/openmetrics-text``): counter TYPE lines drop the
    ``_total`` suffix, the exposition ends with ``# EOF``, and kept
    tail-trace ids render as ``# {trace_id=...}`` EXEMPLARS on the
    histogram bucket lines — exemplar syntax is ONLY legal there, so
    the classic 0.0.4 rendering omits them (a 0.0.4 parser would
    reject the whole scrape otherwise).

    Every sample carries ``node``/``role`` labels, so one scrape of
    the scheduler covers the whole cluster."""
    counters: Dict[str, list] = {}
    gauges: Dict[str, list] = {}
    hists: Dict[str, list] = {}
    for node_id in sorted(snap):
        s = snap[node_id]
        m = s.get("metrics", {})
        base = {"node": str(node_id), "role": s.get("role", "?")}
        for name, v in sorted(m.get("counters", {}).items()):
            labels = dict(base)
            tm = _TENANT_RE.match(name)
            if tm:
                labels["tenant"] = tm.group("tenant")
                fam = _prom_name(f"tenant.{tm.group('kind')}") + "_total"
            else:
                fam = _prom_name(name) + "_total"
            counters.setdefault(fam, []).append((labels, v))
        for name, v in sorted(m.get("gauges", {}).items()):
            gauges.setdefault(_prom_name(name), []).append((base, v))
        for name, h in sorted(m.get("histograms", {}).items()):
            hists.setdefault(_prom_name(name), []).append((base, h))
        up = m.get("uptime_s")
        if up is not None:
            gauges.setdefault("pslite_uptime_seconds", []).append(
                (base, up))
    out: List[str] = []

    def _esc(v) -> str:
        # Exposition-format label escaping (\\, \", \n) — label values
        # here are identifier-like, but a hostile tenant name must not
        # corrupt the whole scrape.
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def _labels(d: dict) -> str:
        inner = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(d.items()))
        return "{" + inner + "}" if inner else ""

    for fam in sorted(counters):
        # OpenMetrics names the counter FAMILY without the _total
        # suffix its samples carry; 0.0.4 types the sample name.
        tname = fam[:-len("_total")] if openmetrics else fam
        out.append(f"# TYPE {tname} counter")
        for labels, v in counters[fam]:
            out.append(f"{fam}{_labels(labels)} {int(v)}")
    for fam in sorted(gauges):
        out.append(f"# TYPE {fam} gauge")
        for labels, v in gauges[fam]:
            out.append(f"{fam}{_labels(labels)} {_prom_float(v)}")
    for fam in sorted(hists):
        out.append(f"# TYPE {fam} histogram")
        for labels, h in hists[fam]:
            lo = h.get("lo", 1e-6)
            acc = 0
            # Histogram exemplars (docs/observability.md): kept tail
            # trace ids attach to the bucket their latency landed in,
            # rendered in OpenMetrics exemplar syntax — a Prometheus
            # p99 panel links straight to the trace that caused it.
            # OPENMETRICS ONLY: the 0.0.4 text format has no exemplar
            # grammar, and a classic parser rejects the whole scrape.
            ex = ({int(i): (t, v, w)
                   for i, t, v, w in h.get("exemplars") or []}
                  if openmetrics else {})
            for i, n in sorted(
                    (int(i), int(n)) for i, n in h.get("buckets") or []):
                acc += n
                le = _prom_float(lo * (2.0 ** i))
                lb = _labels({**labels, "le": le})
                line = f"{fam}_bucket{lb} {acc}"
                if i in ex:
                    t, v, w = ex[i]
                    line += (f' # {{trace_id="{_esc(t)}"}} '
                             f"{_prom_float(v)} {round(float(w), 3)}")
                out.append(line)
            lb = _labels({**labels, "le": "+Inf"})
            out.append(f"{fam}_bucket{lb} {int(h.get('count', acc))}")
            out.append(f"{fam}_sum{_labels(labels)} "
                       f"{_prom_float(h.get('sum', 0.0))}")
            out.append(f"{fam}_count{_labels(labels)} "
                       f"{int(h.get('count', acc))}")
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


def serve(collect_fn, port: int, host: str = "127.0.0.1"):
    """Start a daemonized ``http.server`` answering ``GET /metrics``
    (and ``/``) with :func:`to_prometheus` over ``collect_fn()``'s
    snapshot.  Returns the live ``ThreadingHTTPServer`` — call
    ``.shutdown()`` to stop; the bound port is ``.server_address[1]``
    (pass ``port=0`` to let the OS pick, e.g. in tests)."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path.split("?")[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            # Content negotiation: a scraper asking for OpenMetrics
            # (Prometheus does when exemplar scraping is on) gets the
            # OM rendering WITH exemplars; everyone else gets classic
            # 0.0.4 text, which has no exemplar grammar.
            om = "openmetrics" in (self.headers.get("Accept") or "")
            try:
                body = to_prometheus(collect_fn(),
                                     openmetrics=om).encode()
            except Exception as exc:  # noqa: BLE001 - a failed pull
                self.send_error(500, explain=repr(exc))  # not a crash
                return
            self.send_response(200)
            self.send_header(
                "Content-Type",
                OPENMETRICS_CONTENT_TYPE if om else PROM_CONTENT_TYPE,
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet scraper chatter
            pass

    httpd = ThreadingHTTPServer((host, port), _Handler)
    t = threading.Thread(target=httpd.serve_forever,
                         name="psmon-serve", daemon=True)
    t.start()
    return httpd


# -- CLI demo ----------------------------------------------------------------


def _demo(args) -> int:
    """Boot a live 2w+2s LoopbackCluster, run a short storm, and render
    through the chosen mode.  The standalone proof of the pull plane
    (library callers attach to their own scheduler instead)."""
    import numpy as np

    from pslite_tpu.benchmark import _loopback_cluster, _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker)

    env = {}
    if args.watch:
        # --serve does NOT start the sampler: scrapes pull on demand
        # through collect(), and a background sampler would only burn
        # a cluster-wide METRICS_PULL per interval alongside them.
        env["PS_METRICS_INTERVAL"] = str(args.interval)
        # Tail tracing powers the watch footer's critical-path line
        # (tools/pstrace.py is the full explorer).
        env["PS_TRACE_TAIL"] = "slow:p90,errors,floor:0.05"
    nodes = _loopback_cluster(num_workers=2, num_servers=2,
                              ns="psmon-demo", env_extra=env)
    scheduler, server_pos, worker_pos = nodes[0], nodes[1:3], nodes[3:]
    servers = []
    workers = []
    try:
        for po in server_pos:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        workers = [KVWorker(0, 0, postoffice=po) for po in worker_pos]
        keys = np.array([3, 2 ** 62, 2 ** 63 + 9], dtype=np.uint64)
        vals = np.ones(3 * 128, dtype=np.float32)
        out = np.zeros_like(vals)
        for _ in range(20):
            for w in workers:
                w.wait(w.push(keys, vals))
        workers[0].wait(workers[0].pull(keys, out))
        if args.serve is not None:
            httpd = serve(lambda: collect(scheduler), args.serve)
            port = httpd.server_address[1]
            print(f"psmon: serving Prometheus text on "
                  f"http://127.0.0.1:{port}/metrics (Ctrl-C to stop)")
            try:
                while True:
                    for w in workers:  # keep the cluster lively
                        w.wait(w.push(keys, vals))
                    time.sleep(max(args.interval, 0.5))
            except KeyboardInterrupt:
                pass
            finally:
                httpd.shutdown()
        elif args.watch:
            history = scheduler.start_history(interval_s=args.interval)
            try:
                for _ in range(args.rounds):
                    for w in workers:
                        w.wait(w.push(keys, vals))
                    time.sleep(args.interval)
                    traces = scheduler.collect_cluster_traces(
                        timeout_s=args.interval)
                    sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
                    print(format_watch(history, traces=traces))
            except KeyboardInterrupt:
                pass
        else:
            snap = collect(scheduler)
            if args.wire:
                print(format_wire(snap))
            elif args.json:
                print(to_json(snap))
            else:
                print(format_table(snap, stale=stale_ages(scheduler, snap)))
    finally:
        _teardown_cluster(nodes, workers, servers)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="dump the raw snapshot as JSON")
    ap.add_argument("--watch", action="store_true",
                    help="live refreshing table with windowed rates, "
                         "sparklines, and the health-event footer")
    ap.add_argument("--wire", action="store_true",
                    help="wire-plane table: syscalls/op, frames/op, "
                         "batch fill, lane residency p99 per node and "
                         "plane (docs/observability.md)")
    ap.add_argument("--serve", type=int, metavar="PORT", default=None,
                    help="serve OpenMetrics/Prometheus text exposition "
                         "on PORT (0 = OS-assigned)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="sampling interval for --watch/--serve (s)")
    ap.add_argument("--rounds", type=int, default=10,
                    help="--watch refresh count before exiting")
    args = ap.parse_args(argv)
    return _demo(args)


if __name__ == "__main__":
    sys.exit(main())
