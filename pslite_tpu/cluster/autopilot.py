"""Scheduler-side autopilot: the sense→decide→act loop
(docs/autopilot.md).

PRs 9–16 gave the scheduler both halves of a control loop — the senses
(ClusterHistory windowed rates/quantiles, the SLO watchdog,
critical-path attribution, the flight recorder) and the actuators
(routing epochs with live range migration, elastic join/decommission,
coordinated snapshots, apply-shard retune) — but an operator still
pulled every lever.  :class:`Autopilot` closes the loop: it rides the
ClusterHistory sampler (``observe`` runs after every watchdog
evaluation) and grades a small set of declarative rules against the
freshest window:

- ``hot_skew``     sustained per-server request-rate skew → split/move
                   the hot rank's most loaded range to the coldest rank
                   (a new routing epoch; the existing migration
                   machinery performs the handoff).
- ``shed_scale``   sustained shed-rate CRIT → scale OUT through the
                   pluggable ``spawn_server`` actuator (the tracker, or
                   an in-process launcher in tests/benches).
- ``scale_in``     sustained idleness (opt-in watermark) → retire the
                   least-loaded rank through ``retire_server``.
- ``snapshot_age`` durable-tier staleness → schedule a snapshot, with
                   exponential backoff while the cut keeps getting
                   vetoed (quiesce-fence pressure, migrations in
                   flight).
- ``apply_wait``   critical-path dominance of the apply-shard wait
                   stage → halve the apply task quantum cluster-wide.
- ``apply_widen``  the symmetric recovery: apply-wait share collapsed
                   with the quantum narrowed → double it back toward
                   the configured baseline, same guardrails.

Safety is the point, not the afterthought:

- **Hysteresis**: a rule must trip on ``sustain`` CONSECUTIVE samples
  before it may act; one noisy window never moves data.
- **Per-rule cooldown**: after an action (or a veto) the rule re-arms
  only after ``cooldown_s`` AND a fresh sustained streak.
- **Global budget**: at most ``PS_AUTOPILOT_MAX_ACTIONS`` actions per
  ``PS_AUTOPILOT_WINDOW_S`` across ALL rules — a sick signal cannot
  melt the cluster with remediation.
- **Dry run**: ``PS_AUTOPILOT=plan`` decides (and consumes budget)
  exactly like ``=1`` but never acts — the narration shows what WOULD
  have happened.
- **Kill switch**: with ``PS_AUTOPILOT`` unset nothing is constructed,
  registered, or sent — bit-identical to a cluster without this file.

Every decision AND every veto lands as a structured flight-recorder
event (``autopilot``) and a health INFO event, so ``psmon --watch``
and postmortems can narrate the loop.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils import logging as log

# Mirrors telemetry.health severities without importing at module load.
_INFO, _WARN = "info", "warn"

# Outcomes a decision can land on.
ACTED = "acted"        # actuator invoked and returned
PLANNED = "planned"    # dry-run: would have acted
VETOED = "vetoed"      # a guardrail or precondition said no
FAILED = "failed"      # actuator raised


def parse_mode(raw: Optional[str]) -> Optional[str]:
    """``PS_AUTOPILOT`` → ``None`` (off) / ``"plan"`` / ``"act"``.

    Unrecognized spellings are FATAL, not coerced: silently reading a
    typo'd ``paln`` as act mode would turn an intended dry run into
    live actuation — the one direction a safety knob must never
    default."""
    if raw is None:
        return None
    raw = raw.strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return None
    if raw in ("plan", "dry", "dryrun", "dry-run"):
        return "plan"
    if raw in ("1", "act", "on", "true", "yes"):
        return "act"
    log.check(False, f"PS_AUTOPILOT={raw!r} is not a recognized mode "
                     f"(1/act/on, plan/dry-run, or 0/off/unset)")


class Veto(Exception):
    """An actuator's precondition failed — a POLICY decline (recorded
    as a veto), not an execution error."""


class Decision:
    """One autopilot verdict — what a rule proposed and what happened
    to the proposal."""

    __slots__ = ("wall", "rule", "action", "outcome", "reason", "detail")

    def __init__(self, wall: float, rule: str, action: str, outcome: str,
                 reason: str, detail: Optional[dict] = None):
        self.wall = wall
        self.rule = rule
        self.action = action
        self.outcome = outcome
        self.reason = reason
        self.detail = detail or {}

    def as_dict(self) -> dict:
        return {
            "wall": self.wall, "rule": self.rule, "action": self.action,
            "outcome": self.outcome, "reason": self.reason,
            "detail": dict(self.detail),
        }

    def __repr__(self) -> str:
        return (f"<Decision {self.rule}:{self.action} {self.outcome} "
                f"({self.reason})>")


class PolicyRule:
    """Base rule: subclasses implement ``sense`` (proposal or None)
    and ``act`` (raise :class:`Veto` for precondition declines)."""

    name = "rule"

    def __init__(self, sustain: int, cooldown_s: float):
        self.sustain = max(1, int(sustain))
        self.cooldown_s = float(cooldown_s)
        self.streak = 0           # consecutive breaching samples
        self.last_fired = -1e18   # wall of the last decision (any outcome)

    def effective_cooldown(self) -> float:
        return self.cooldown_s

    def sense(self, ap: "Autopilot", history, wall: float) -> Optional[dict]:
        raise NotImplementedError

    def act(self, ap: "Autopilot", proposal: dict) -> None:
        raise NotImplementedError

    # Backoff hooks — only snapshot_age overrides them today.
    def on_result(self, ok: bool) -> None:
        pass


def _server_rates(history, counters=("kv.server_push_requests",
                                     "kv.server_pull_requests")):
    """``{node_id: windowed request rate}`` for every server the
    history has ≥2 samples of (None-rate nodes are skipped — a node
    with one sample must not read as idle)."""
    rates: Dict[int, float] = {}
    for nid in history.node_ids():
        if history.role_of(nid) != "server":
            continue
        total, seen = 0.0, False
        for c in counters:
            r = history.rate(nid, c)
            if r is not None:
                total += r
                seen = True
        if seen:
            rates[nid] = total
    return rates


def _hot_hint(history) -> Dict[int, int]:
    """Union of ``kv.hot_keys`` top-k estimates across the freshest
    server snapshots (the same shape as ``Postoffice.hot_key_hint``,
    but sourced from the history so synthetic feeds work)."""
    hint: Dict[int, int] = {}
    for nid in history.node_ids():
        m = history.latest(nid) or {}
        for item in (m.get("topk", {}) or {}).get("kv.hot_keys") or []:
            try:
                k, n = int(item[0]), int(item[1])
            except (TypeError, ValueError, IndexError):
                continue
            hint[k] = hint.get(k, 0) + n
    return hint


class HotSkewRule(PolicyRule):
    """Sustained per-server request-rate skew → move/split the hot
    rank's most loaded range to the coldest rank."""

    name = "hot_skew"

    def __init__(self, env):
        super().__init__(
            sustain=env.find_int("PS_AUTOPILOT_SUSTAIN", 3),
            cooldown_s=env.find_float("PS_AUTOPILOT_SKEW_COOLDOWN_S", 20.0),
        )
        self.ratio = env.find_float("PS_AUTOPILOT_SKEW_RATIO", 2.0)
        # Below this aggregate rate the cluster is idle — rebalancing
        # noise-level traffic just churns epochs.
        self.min_rate = env.find_float("PS_AUTOPILOT_MIN_RATE", 1.0)

    def sense(self, ap, history, wall):
        rates = _server_rates(history)
        if len(rates) < 2 or sum(rates.values()) < self.min_rate:
            return None
        mean = sum(rates.values()) / len(rates)
        hot_nid = max(rates, key=rates.get)
        cold_nid = min(rates, key=rates.get)
        if mean <= 0 or rates[hot_nid] < self.ratio * mean:
            return None
        from ..base import id_to_rank
        return {
            "action": "rebalance",
            "reason": (f"server {hot_nid} at {rates[hot_nid]:.1f} req/s "
                       f"≥ {self.ratio:g}x mean {mean:.1f}"),
            "src": id_to_rank(hot_nid) // ap.po.group_size,
            "dst": id_to_rank(cold_nid) // ap.po.group_size,
            "skew": round(rates[hot_nid] / max(mean, 1e-9), 2),
        }

    def act(self, ap, proposal):
        po = ap.po
        table = po.routing_table()
        if table is None:
            raise Veto("static routing (PS_ELASTIC=0) — no epoch to derive")
        # The live ledger, not the table's prev markers: markers persist
        # on the CURRENT epoch long after the handoff landed (the next
        # epoch derives from the settled base), but the ledger clears on
        # MIGRATE_DONE and expires after PS_MIGRATION_SETTLE_S.
        pending = po.migrations_in_flight()
        if pending:
            raise Veto(f"{len(pending)} range migration(s) still in "
                       f"flight (epoch {table.epoch})")
        hot = _hot_hint(ap.history_ref) if ap.history_ref is not None else {}
        if not hot:
            hot = po.hot_key_hint()
        new = table.with_rebalance(proposal["src"], proposal["dst"],
                                   hot=hot)
        po.van.broadcast_routing(new)
        proposal["epoch"] = new.epoch


class ShedScaleRule(PolicyRule):
    """Sustained shed-rate CRIT (tenant QoS sheds) → scale out through
    the pluggable spawn actuator."""

    name = "shed_scale"

    def __init__(self, env, crit: float):
        super().__init__(
            sustain=env.find_int("PS_AUTOPILOT_SUSTAIN", 3),
            cooldown_s=env.find_float("PS_AUTOPILOT_SCALE_COOLDOWN_S", 60.0),
        )
        self.crit = crit  # the watchdog's shed_rate CRIT threshold

    def sense(self, ap, history, wall):
        worst_nid, worst = None, 0.0
        for nid in history.node_ids():
            if history.role_of(nid) != "server":
                continue
            r = history.rate(nid, "qos.shed_requests")
            if r is not None and r > worst:
                worst_nid, worst = nid, r
        if worst_nid is None or worst < self.crit:
            return None
        return {
            "action": "scale_out",
            "reason": (f"server {worst_nid} shedding {worst:.1f} req/s "
                       f"≥ CRIT {self.crit:g}"),
            "shed_rate": round(worst, 2),
        }

    def act(self, ap, proposal):
        if ap.spawn_server is None:
            raise Veto("no spawn actuator attached (tracker not wired)")
        ap.spawn_server()


class ScaleInRule(PolicyRule):
    """Opt-in scale-in: with every server under the configured
    watermark (``PS_AUTOPILOT_SCALE_IN_RATE`` > 0) and nothing
    shedding, retire the least-loaded rank.  Disabled by default —
    shrinking a healthy cluster is never urgent."""

    name = "scale_in"

    def __init__(self, env):
        super().__init__(
            sustain=env.find_int("PS_AUTOPILOT_SCALE_IN_SUSTAIN", 10),
            cooldown_s=env.find_float("PS_AUTOPILOT_SCALE_COOLDOWN_S", 60.0),
        )
        self.watermark = env.find_float("PS_AUTOPILOT_SCALE_IN_RATE", 0.0)
        self.min_servers = env.find_int("PS_AUTOPILOT_MIN_SERVERS", 1)

    def sense(self, ap, history, wall):
        if self.watermark <= 0:
            return None
        rates = _server_rates(history)
        if len(rates) <= self.min_servers:
            return None
        if any(r >= self.watermark for r in rates.values()):
            return None
        for nid in rates:
            shed = history.rate(nid, "qos.shed_requests")
            if shed is not None and shed > 0:
                return None
        from ..base import id_to_rank
        idle_nid = min(rates, key=rates.get)
        return {
            "action": "scale_in",
            "reason": (f"all {len(rates)} servers under "
                       f"{self.watermark:g} req/s"),
            "rank": id_to_rank(idle_nid) // ap.po.group_size,
        }

    def act(self, ap, proposal):
        if ap.retire_server is None:
            raise Veto("no retire actuator attached (tracker not wired)")
        table = ap.po.routing_table()
        if table is not None and len(table.active) <= max(
                1, self.min_servers):
            raise Veto(f"already at min_servers={self.min_servers}")
        ap.retire_server(proposal["rank"])


class SnapshotAgeRule(PolicyRule):
    """Durable-tier staleness → schedule a snapshot; exponential
    backoff while the cut keeps getting vetoed (apply-pool quiesce
    pressure, migrations in flight)."""

    name = "snapshot_age"

    def __init__(self, env, warn: float):
        super().__init__(
            sustain=env.find_int("PS_AUTOPILOT_SNAPSHOT_SUSTAIN", 2),
            cooldown_s=env.find_float(
                "PS_AUTOPILOT_SNAPSHOT_COOLDOWN_S", 30.0),
        )
        self.age_s = warn  # the watchdog's snapshot_age WARN threshold
        self.backoff = 1
        self.backoff_max = env.find_int("PS_AUTOPILOT_BACKOFF_MAX", 16)

    def effective_cooldown(self) -> float:
        return self.cooldown_s * self.backoff

    def on_result(self, ok: bool) -> None:
        # Quiesce-fence pressure is the backoff signal: a vetoed cut
        # (busy apply pool, migration mid-handoff) doubles the retry
        # horizon; a committed cut resets it.
        self.backoff = 1 if ok else min(self.backoff * 2,
                                        self.backoff_max)

    def sense(self, ap, history, wall):
        worst = None
        for nid in history.node_ids():
            m = history.latest(nid) or {}
            age = m.get("gauges", {}).get("snapshot.age_s")
            if age is None:
                continue
            age = float(age)
            # Negative = configured but never committed: infinitely
            # stale for scheduling purposes.
            age = float("inf") if age < 0 else age
            if worst is None or age > worst:
                worst = age
        if worst is None or worst < self.age_s:
            return None
        pretty = "never" if worst == float("inf") else f"{worst:.0f}s"
        return {
            "action": "snapshot",
            "reason": f"snapshot age {pretty} ≥ {self.age_s:g}s",
            "backoff": self.backoff,
        }

    def act(self, ap, proposal):
        po = ap.po
        if not po.snapshot_dir:
            raise Veto("no snapshot directory (PS_SNAPSHOT_DIR unset)")
        # po.snapshot blocks on a cluster-wide gather — never on the
        # sampler thread.  The outcome lands as a follow-up flight
        # event and feeds the backoff.
        def _cut():
            try:
                po.snapshot()
            except Exception as exc:  # noqa: BLE001 - veto/timeout
                self.on_result(False)
                ap._record_followup(self, "snapshot", FAILED,
                                    repr(exc)[:160],
                                    backoff=self.backoff)
            else:
                self.on_result(True)
                ap._record_followup(self, "snapshot", ACTED,
                                    "cut committed")
        threading.Thread(target=_cut, name="autopilot-snapshot",
                         daemon=True).start()


class ApplyWaitRule(PolicyRule):
    """Critical-path dominance of the apply-shard wait stage → halve
    the apply task quantum cluster-wide (smaller tasks preempt
    sooner; docs/apply_shards.md)."""

    name = "apply_wait"

    _FLOOR = 64 << 10  # quantum floor: below this, task overhead wins

    def __init__(self, env):
        super().__init__(
            sustain=env.find_int("PS_AUTOPILOT_SUSTAIN", 3),
            cooldown_s=env.find_float(
                "PS_AUTOPILOT_RETUNE_COOLDOWN_S", 60.0),
        )
        self.share = env.find_float("PS_AUTOPILOT_APPLY_WAIT_SHARE", 0.5)
        self.min_traces = env.find_int("PS_AUTOPILOT_MIN_TRACES", 8)

    def sense(self, ap, history, wall):
        agg = ap.trace_aggregate()
        if not agg or agg.get("count", 0) < self.min_traces:
            return None
        info = (agg.get("slow") or {}).get("apply_wait") or {}
        share = float(info.get("share", 0.0))
        if share < self.share:
            return None
        return {
            "action": "retune_apply",
            "reason": (f"apply_wait is {share * 100:.0f}% of the "
                       f"slow-quartile wall (≥ {self.share * 100:.0f}%)"),
            "share": round(share, 3),
        }

    def act(self, ap, proposal):
        cur = ap.apply_task_bytes
        if cur <= self._FLOOR:
            raise Veto(f"apply quantum already at floor ({cur} B)")
        new = max(self._FLOOR, cur // 2)
        ap.po.retune_apply(new)
        ap.apply_task_bytes = new
        proposal["task_bytes"] = new


class ApplyWidenRule(PolicyRule):
    """Symmetric recovery for :class:`ApplyWaitRule`: when the
    apply-wait share of the slow-quartile wall has COLLAPSED and the
    quantum sits below its configured baseline, double it back toward
    the baseline (bigger tasks amortize dispatch overhead;
    docs/apply_shards.md).  Same sustain/cooldown guardrails as the
    narrowing rule, so a transient lull can't thrash the quantum."""

    name = "apply_widen"

    def __init__(self, env):
        super().__init__(
            sustain=env.find_int("PS_AUTOPILOT_SUSTAIN", 3),
            cooldown_s=env.find_float(
                "PS_AUTOPILOT_RETUNE_COOLDOWN_S", 60.0),
        )
        self.share = env.find_float("PS_AUTOPILOT_APPLY_WIDEN_SHARE",
                                    0.15)
        self.min_traces = env.find_int("PS_AUTOPILOT_MIN_TRACES", 8)
        # The quantum the operator configured — the ceiling widening
        # converges back to, never beyond.
        self.baseline = env.find_int("PS_APPLY_TASK_BYTES", 2 << 20)

    def sense(self, ap, history, wall):
        if ap.apply_task_bytes >= self.baseline:
            return None  # nothing was narrowed; nothing to undo
        agg = ap.trace_aggregate()
        if not agg or agg.get("count", 0) < self.min_traces:
            return None  # no evidence the pressure is gone — hold
        info = (agg.get("slow") or {}).get("apply_wait") or {}
        share = float(info.get("share", 0.0))
        if share > self.share:
            return None
        return {
            "action": "retune_apply",
            "reason": (f"apply_wait fell to {share * 100:.0f}% of the "
                       f"slow-quartile wall (≤ {self.share * 100:.0f}%) "
                       f"with the quantum narrowed"),
            "share": round(share, 3),
        }

    def act(self, ap, proposal):
        cur = ap.apply_task_bytes
        if cur >= self.baseline:
            raise Veto(f"apply quantum already at baseline ({cur} B)")
        new = min(self.baseline, cur * 2)
        ap.po.retune_apply(new)
        ap.apply_task_bytes = new
        proposal["task_bytes"] = new


class Autopilot:
    """The policy engine.  Constructed by ``Postoffice.start_history``
    when ``PS_AUTOPILOT`` is set; ``observe`` rides every
    ``ClusterHistory.ingest`` (after the watchdog)."""

    def __init__(self, po, env=None, mode: str = "act"):
        env = env if env is not None else po.env
        self.po = po
        self.mode = mode
        self.history_ref = None  # set when attached to a ClusterHistory
        # Pluggable scale actuators (the tracker, or in-process fakes
        # in tests/benches).  Decisions veto loudly when absent.
        self.spawn_server: Optional[Callable[[], None]] = None
        self.retire_server: Optional[Callable[[int], None]] = None
        # Global action budget: across ALL rules.
        self.max_actions = env.find_int("PS_AUTOPILOT_MAX_ACTIONS", 4)
        self.window_s = env.find_float("PS_AUTOPILOT_WINDOW_S", 60.0)
        self._action_walls: collections.deque = collections.deque(
            maxlen=max(16, self.max_actions * 4))
        self.decision_log: collections.deque = collections.deque(
            maxlen=env.find_int("PS_AUTOPILOT_LOG", 128))
        # The apply quantum the fleet currently runs (scheduler's view;
        # retunes keep it in step).
        self.apply_task_bytes = env.find_int("PS_APPLY_TASK_BYTES",
                                             2 << 20)
        # Trace aggregation source for apply_wait (injectable in
        # tests): default pulls the scheduler's trace collector at most
        # every trace_every-th observe round.
        self.trace_every = env.find_int("PS_AUTOPILOT_TRACE_EVERY", 4)
        self.trace_source: Optional[Callable[[], dict]] = None
        self._trace_agg: dict = {}
        self._observes = 0
        self._mu = threading.Lock()

        from ..telemetry.health import DEFAULT_THRESHOLDS
        wd_rules = getattr(po, "history", None)
        wd_rules = (wd_rules.watchdog.rules
                    if wd_rules is not None else None)

        def _thresh(rule, idx):
            if wd_rules is not None and rule in wd_rules:
                r = wd_rules[rule]
                return r.crit if idx else r.warn
            return DEFAULT_THRESHOLDS[rule][idx]

        self.rules: List[PolicyRule] = [
            HotSkewRule(env),
            ShedScaleRule(env, crit=_thresh("shed_rate", 1)),
            ScaleInRule(env),
            SnapshotAgeRule(env, warn=_thresh("snapshot_age", 0)),
            ApplyWaitRule(env),
            ApplyWidenRule(env),
        ]
        disabled = {
            r.strip() for r in
            (env.find("PS_AUTOPILOT_DISABLE") or "").split(",")
            if r.strip()
        }
        known = {r.name for r in self.rules}
        bad = disabled - known
        log.check(not bad, f"unknown PS_AUTOPILOT_DISABLE rule(s) "
                           f"{sorted(bad)} (known: {sorted(known)})")
        self.rules = [r for r in self.rules if r.name not in disabled]

    # -- sensing hooks -------------------------------------------------------

    def trace_aggregate(self) -> dict:
        """Freshest critical-path aggregate.  The default source pulls
        the scheduler's live trace collector (TRACE_PULL) every
        ``trace_every``-th observe round — trace assembly is too heavy
        for every sample.  Tests inject ``trace_source``."""
        if self.trace_source is not None:
            try:
                self._trace_agg = self.trace_source() or {}
            except Exception as exc:  # noqa: BLE001 - a bad source
                log.vlog(1, f"autopilot trace source failed: {exc!r}")
            return self._trace_agg
        if self.trace_every <= 0:
            return {}
        if self._observes % self.trace_every == 0:
            try:
                coll = self.po.collect_cluster_traces(timeout_s=2.0)
                self._trace_agg = coll.aggregate()
            except Exception as exc:  # noqa: BLE001 - mid-teardown van
                log.vlog(1, f"autopilot trace pull failed: {exc!r}")
        return self._trace_agg

    # -- the loop ------------------------------------------------------------

    def observe(self, history, wall: Optional[float] = None) -> List[Decision]:
        """Grade every rule against the history's freshest window.
        Called by ``ClusterHistory.ingest`` (sampler thread or a
        synthetic test feed); returns the decisions made this round."""
        wall = time.time() if wall is None else float(wall)
        if not self._mu.acquire(blocking=False):
            return []  # a slow actuator round must not stack observers
        try:
            self.history_ref = history
            out: List[Decision] = []
            for rule in self.rules:
                try:
                    proposal = rule.sense(self, history, wall)
                except Exception as exc:  # noqa: BLE001 - one broken
                    # sensor must not blind the others.
                    log.warning(f"autopilot {rule.name}.sense failed: "
                                f"{exc!r}")
                    continue
                if proposal is None:
                    rule.streak = 0
                    continue
                rule.streak += 1
                if rule.streak < rule.sustain:
                    log.vlog(1, f"autopilot {rule.name} arming "
                                f"{rule.streak}/{rule.sustain}: "
                                f"{proposal['reason']}")
                    continue
                d = self._decide(rule, proposal, wall)
                out.append(d)
            self._observes += 1
            return out
        finally:
            self._mu.release()

    def _decide(self, rule: PolicyRule, proposal: dict,
                wall: float) -> Decision:
        action = proposal.pop("action")
        reason = proposal.pop("reason")
        # A decision point always resets the streak: the rule must
        # re-sustain before its next consideration (this also rate-
        # limits repeated veto narration to once per sustained streak).
        rule.streak = 0
        if wall - rule.last_fired < rule.effective_cooldown():
            remain = rule.effective_cooldown() - (wall - rule.last_fired)
            return self._record(wall, rule, action, VETOED, reason,
                                veto=f"cooldown ({remain:.0f}s left)",
                                **proposal)
        recent = [w for w in self._action_walls
                  if wall - w < self.window_s]
        if len(recent) >= self.max_actions:
            return self._record(
                wall, rule, action, VETOED, reason,
                veto=(f"budget ({self.max_actions} actions/"
                      f"{self.window_s:.0f}s exhausted)"),
                **proposal)
        rule.last_fired = wall
        # Plan mode consumes budget too: the dry-run narration must
        # match what act mode would actually have done.
        self._action_walls.append(wall)
        if self.mode == "plan":
            return self._record(wall, rule, action, PLANNED, reason,
                                **proposal)
        try:
            rule.act(self, proposal)
        except Veto as v:
            self._action_walls.pop()  # a vetoed action spent nothing
            return self._record(wall, rule, action, VETOED, reason,
                                veto=str(v), **proposal)
        except Exception as exc:  # noqa: BLE001 - actuator failure
            log.warning(f"autopilot {rule.name}.act failed: {exc!r}")
            return self._record(wall, rule, action, FAILED, reason,
                                error=repr(exc)[:160], **proposal)
        return self._record(wall, rule, action, ACTED, reason, **proposal)

    # -- narration -----------------------------------------------------------

    def _record(self, wall: float, rule: PolicyRule, action: str,
                outcome: str, reason: str, **detail) -> Decision:
        d = Decision(wall, rule.name, action, outcome, reason, detail)
        self.decision_log.append(d)
        sev = _INFO if outcome in (ACTED, PLANNED) else _WARN
        self.po.flight.record("autopilot", severity=sev, rule=rule.name,
                              action=action, outcome=outcome,
                              reason=reason, **detail)
        hist = self.history_ref
        if hist is not None:
            hist.watchdog._emit(
                wall, _INFO, f"autopilot.{rule.name}", node_id=-1,
                role="scheduler", metric=action, value=0.0,
                threshold=0.0, window_s=hist.default_window_s,
                message=f"{outcome}: {reason}"
                        + (f" — {detail['veto']}" if "veto" in detail
                           else ""),
            )
        log.vlog(0 if outcome in (ACTED, FAILED) else 1,
                 f"autopilot {rule.name}:{action} {outcome} — {reason}"
                 + (f" ({detail.get('veto') or detail.get('error')})"
                    if outcome in (VETOED, FAILED) else ""))
        return d

    def _record_followup(self, rule: PolicyRule, action: str,
                         outcome: str, reason: str, **detail) -> None:
        """Async actuator completion (the snapshot thread) — narrated
        like a decision so the flight log shows the whole arc."""
        self._record(time.time(), rule, action, outcome, reason,
                     followup=True, **detail)

    def decisions(self, n: int = 8) -> List[Decision]:
        """The last ``n`` decisions, oldest first (psmon's footer)."""
        return list(self.decision_log)[-max(0, n):]

    def counts(self) -> Dict[str, int]:
        c: Dict[str, int] = {}
        for d in self.decision_log:
            c[d.outcome] = c.get(d.outcome, 0) + 1
        return c
