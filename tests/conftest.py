"""Test bootstrap: force the CPU backend with 8 virtual devices.

Sharding/collective tests run on a virtual 8-device CPU mesh; real-TPU
benchmarking happens in bench.py (which does NOT import this).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest


@pytest.fixture(autouse=True)
def _loopback_isolation(request):
    """Give each test its own loopback namespace and clean registry."""
    os.environ["PS_LOOPBACK_NS"] = request.node.nodeid
    yield
    from pslite_tpu.vans import loopback_van

    loopback_van.reset_registry()
    os.environ.pop("PS_LOOPBACK_NS", None)
