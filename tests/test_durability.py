"""Coordinated cluster snapshots + restore-on-boot
(pslite_tpu/kv/snapshot.py, docs/durability.md).

The headline contract: kill the WHOLE cluster, boot a fresh one with
``PS_SNAPSHOT_RESTORE=1``, and every range restores bit-exact —
optimizer slots included, and a snapshot racing a push storm captures
a consistent cut (every request entirely before or after it).  A
corrupt snapshot fails the restore loudly instead of serving garbage.
"""

import json
import os
import threading

import numpy as np
import pytest

from helpers import LoopbackCluster
from pslite_tpu.kv.kv_app import (KVMeta, KVServer,
                                  KVServerDefaultHandle,
                                  KVServerOptimizerHandle, KVWorker,
                                  _push_segs)
from pslite_tpu.kv import snapshot as snap_mod
from pslite_tpu.utils import logging as log


def _boot(snapdir, num_servers=1, extra=None, handle_factory=None):
    env = {"PS_SNAPSHOT_DIR": snapdir}
    env.update(extra or {})
    cl = LoopbackCluster(num_workers=1, num_servers=num_servers,
                         env_extra=env)
    cl.start()
    servers = []
    for po in cl.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(
            handle_factory() if handle_factory
            else KVServerDefaultHandle()
        )
        servers.append(s)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    return cl, servers, w


def _kill(cl, servers):
    cl.finalize()
    for s in servers:
        s.stop()


def test_full_cluster_kill_restore_bit_exact(tmp_path):
    snapdir = str(tmp_path / "snap")
    cl, servers, w = _boot(snapdir, num_servers=2)
    keys = np.array([1, 5, 9, 2**62, 2**63 + 7], dtype=np.uint64)
    vals = np.random.default_rng(0).normal(
        size=len(keys) * 32).astype(np.float32)
    try:
        w.wait(w.push(keys, vals))
        res = cl.scheduler.snapshot()
        assert res["servers"] == 2
        assert os.path.exists(res["manifest"])
        # The scheduler flight-records the commit (docs/durability.md).
        kinds = [e["kind"] for e in cl.scheduler.flight.events()]
        assert "snapshot_begin" in kinds and "snapshot_end" in kinds
        expect = np.zeros_like(vals)
        w.wait(w.pull(keys, expect))
    finally:
        _kill(cl, servers)

    cl2, servers2, w2 = _boot(snapdir, num_servers=2,
                              extra={"PS_SNAPSHOT_RESTORE": "1"})
    try:
        out = np.zeros_like(vals)
        w2.wait(w2.pull(keys, out))
        assert np.array_equal(out, expect)
        # Servers flight-record the boot restore.
        kinds = [e["kind"] for e in cl2.servers[0].flight.events()]
        assert "restore_begin" in kinds and "restore_end" in kinds
        # The age gauge reports a committed manifest.
        assert cl2.scheduler.snapshot_status()["age_s"] >= 0
    finally:
        _kill(cl2, servers2)


def test_restore_includes_optimizer_slots(tmp_path):
    """Adam slots (m, v, step) ride the snapshot: a restored server's
    NEXT update must be bit-exact vs an uninterrupted handle applying
    the identical gradient sequence."""
    snapdir = str(tmp_path / "snap")
    factory = lambda: KVServerOptimizerHandle(kind="adam", lr=0.05)  # noqa: E731
    keys = np.array([2, 7, 11], dtype=np.uint64)
    rng = np.random.default_rng(3)
    grads = [rng.normal(size=len(keys) * 16).astype(np.float32)
             for _ in range(6)]

    reference = factory()
    for g in grads:
        meta = KVMeta(push=True)
        reference.apply_shard(meta, keys,
                              _push_segs(meta, keys, g))

    cl, servers, w = _boot(snapdir, handle_factory=factory)
    try:
        for g in grads[:5]:
            w.wait(w.push(keys, g))
        cl.scheduler.snapshot()
    finally:
        _kill(cl, servers)

    cl2, servers2, w2 = _boot(snapdir, handle_factory=factory,
                              extra={"PS_SNAPSHOT_RESTORE": "1"})
    try:
        w2.wait(w2.push(keys, grads[5]))  # the post-restore step
        out = np.zeros(len(keys) * 16, np.float32)
        w2.wait(w2.pull(keys, out))
        want = np.concatenate([reference.store[int(k)] for k in keys])
        assert np.array_equal(out, want)
        # The step counter itself round-tripped exactly.
        assert servers2[0]._handle._t == {int(k): 6 for k in keys}
    finally:
        _kill(cl2, servers2)


def test_snapshot_racing_push_storm_is_consistent_cut(tmp_path):
    """Chaos half of the acceptance: a snapshot taken MID push storm
    captures every request entirely before or after the fence — with
    each request adding 1.0 to every key of one server, a consistent
    cut restores a store whose keys all hold the SAME count."""
    snapdir = str(tmp_path / "snap")
    cl, servers, w = _boot(snapdir, extra={"PS_APPLY_SHARDS": "4"})
    keys = np.arange(8, dtype=np.uint64)
    ones = np.ones(8 * 64, np.float32)
    n_pushes = 120
    try:
        w.wait(w.push(keys, ones))  # the cut is never empty
        stop = threading.Event()

        def storm():
            pending = []
            for _ in range(n_pushes):
                pending.append(w.push(keys, ones))
                if len(pending) >= 16:
                    w.wait(pending.pop(0))
            for ts in pending:
                w.wait(ts)
            stop.set()

        t = threading.Thread(target=storm, daemon=True)
        t.start()
        cl.scheduler.snapshot()
        t.join(timeout=60)
        assert stop.is_set()
    finally:
        _kill(cl, servers)

    cl2, servers2, w2 = _boot(snapdir,
                              extra={"PS_SNAPSHOT_RESTORE": "1"})
    try:
        out = np.zeros(8 * 64, np.float32)
        w2.wait(w2.pull(keys, out))
        per_key = out.reshape(8, 64)
        count = per_key[0, 0]
        # Every key of every request moved together: a torn request
        # would leave keys at different counts.
        assert np.all(per_key == count), per_key[:, 0]
        assert 1.0 <= count <= n_pushes + 1
    finally:
        _kill(cl2, servers2)


def test_digest_mismatch_fails_restore_loudly(tmp_path):
    snapdir = str(tmp_path / "snap")
    cl, servers, w = _boot(snapdir)
    keys = np.array([4, 8], dtype=np.uint64)
    try:
        w.wait(w.push(keys, np.ones(2 * 16, np.float32)))
        cl.scheduler.snapshot()
    finally:
        _kill(cl, servers)

    # Tamper the committed manifest: the restore must refuse, not
    # serve silently corrupted parameters.
    mpath = os.path.join(snapdir, snap_mod.MANIFEST_NAME)
    doc = json.load(open(mpath))
    doc["ranges"][0]["digest"] = "00000000"
    json.dump(doc, open(mpath, "w"))

    env = {"PS_SNAPSHOT_DIR": snapdir, "PS_SNAPSHOT_RESTORE": "1"}
    cl2 = LoopbackCluster(num_workers=1, num_servers=1, env_extra=env)
    cl2.start()
    s = KVServer(0, postoffice=cl2.servers[0])
    try:
        with pytest.raises(log.CheckError, match="digest mismatch"):
            s.set_request_handle(KVServerDefaultHandle())
    finally:
        cl2.finalize(do_barrier=False)
        s.stop()


def test_partial_snapshot_never_commits(tmp_path):
    """A server that errors (no handle installed) vetoes the commit:
    no manifest appears, and the scheduler raises."""
    snapdir = str(tmp_path / "snap")
    env = {"PS_SNAPSHOT_DIR": snapdir}
    cl = LoopbackCluster(num_workers=1, num_servers=1, env_extra=env)
    cl.start()
    s = KVServer(0, postoffice=cl.servers[0])  # handle never set
    try:
        with pytest.raises(log.CheckError, match="NOT committed"):
            cl.scheduler.snapshot(timeout_s=20.0)
        assert snap_mod.load_manifest(snapdir) is None
    finally:
        cl.finalize()
        s.stop()


def test_vetoed_attempt_never_clobbers_committed_snapshot(tmp_path):
    """Segment filenames are stamped with a per-attempt uid: a later
    attempt whose commit gets vetoed (one server wrote, a sibling
    errored) must leave the previously COMMITTED snapshot restorable,
    and the next committed snapshot prunes the orphans."""
    snapdir = str(tmp_path / "snap")
    keys = np.array([3, 6], dtype=np.uint64)
    vals = np.arange(2 * 16, dtype=np.float32)
    cl, servers, w = _boot(snapdir)
    try:
        w.wait(w.push(keys, vals))
        cl.scheduler.snapshot()
    finally:
        _kill(cl, servers)

    committed = snap_mod.load_manifest(snapdir)
    entry = committed["ranges"][0]
    # Simulate the vetoed attempt's survivor: same range, fresh uid,
    # garbage contents.  The committed segment must be untouched.
    orphan = snap_mod.write_range_segment(
        snapdir, entry["begin"], entry["end"],
        np.array([3], np.uint64), np.full(16, 99.0, np.float32),
        None, uid="vetoedattempt",
    )
    assert orphan["file"] != entry["file"]
    snap_mod.read_range_segment(snapdir, entry)  # digest still good

    cl2, servers2, w2 = _boot(snapdir,
                              extra={"PS_SNAPSHOT_RESTORE": "1"})
    try:
        out = np.zeros_like(vals)
        w2.wait(w2.pull(keys, out))
        assert np.array_equal(out, vals)
        # A second COMMITTED snapshot prunes everything it does not
        # reference: the old committed segment and the orphan.
        res2 = cl2.scheduler.snapshot()
        names = set(os.listdir(snapdir))
        for e in res2["ranges"]:
            assert f"{e['file']}.npz" in names
        assert f"{entry['file']}.npz" not in names
        assert f"{orphan['file']}.npz" not in names
    finally:
        _kill(cl2, servers2)


def test_params_only_source_imports_with_fresh_slots():
    """The length-collision case the lens sign tag exists for: an
    even-length params-only record must import as FULL params with
    fresh slots, never mis-split into [p, m]."""
    from pslite_tpu.kv import replication as repl

    src = KVServerDefaultHandle()
    src.store[5] = np.arange(4, dtype=np.float32)
    keys, vals, lens = repl.export_range(src, 0, 2**64)
    assert lens[0] == 4  # params-only exports POSITIVE lens
    dst = KVServerOptimizerHandle(kind="sgd_momentum")
    dst.import_range(keys, vals, lens)
    assert np.array_equal(dst.store[5], np.arange(4, dtype=np.float32))
    assert 5 not in dst._m  # fresh slots, like a first push


def test_slot_packed_records_tagged_and_kind_mismatch_is_loud():
    h = KVServerOptimizerHandle(kind="sgd_momentum", lr=0.1)
    keys = np.array([9], dtype=np.uint64)
    meta = KVMeta(push=True)
    h.apply_shard(meta, keys,
                  _push_segs(meta, keys, np.ones(9, np.float32)))
    k, v, lens = h.export_range(0, 2**64)
    assert lens[0] == -19  # [p, m, kind_bits], tagged by the sign
    # Same-kind roundtrip restores params AND slots bit-exact.
    twin = KVServerOptimizerHandle(kind="sgd_momentum", lr=0.1)
    twin.import_range(k, v, lens)
    assert np.array_equal(twin.store[9], h.store[9])
    assert np.array_equal(twin._m[9], h._m[9])
    # A mismatched kind REFUSES the tagged record via the embedded
    # kind code — even at lengths where the packings would collide
    # (silently mis-splitting it would corrupt the key).
    with pytest.raises(log.CheckError, match="different optimizer"):
        KVServerOptimizerHandle(kind="adam").import_range(k, v, lens)
    with pytest.raises(log.CheckError, match="sgd"):
        KVServerOptimizerHandle(kind="sgd").import_range(k, v, lens)


def test_plain_store_refuses_slot_packed_records():
    """The generic dict-store import cannot unpack optimizer records:
    storing the raw [p, m, ...] blob as the parameter would silently
    serve momentum state appended to params — it must refuse."""
    from pslite_tpu.kv import replication as repl

    h = KVServerOptimizerHandle(kind="sgd_momentum", lr=0.1)
    keys = np.array([4], dtype=np.uint64)
    meta = KVMeta(push=True)
    h.apply_shard(meta, keys,
                  _push_segs(meta, keys, np.ones(4, np.float32)))
    k, v, lens = h.export_range(0, 2**64)
    with pytest.raises(log.CheckError, match="plain store"):
        repl.import_range(KVServerDefaultHandle(), k, v, lens)


def test_quiesce_timeout_vetoes_the_commit(tmp_path):
    """A fence that cannot drain the apply pool must VETO the cut, not
    export anyway — shard threads still mutating arrays in place would
    commit torn values under a digest that verifies them."""
    snapdir = str(tmp_path / "snap")
    cl, servers, w = _boot(snapdir, extra={"PS_APPLY_SHARDS": "2"})
    try:
        w.wait(w.push(np.array([1], np.uint64),
                      np.ones(16, np.float32)))
        assert servers[0]._apply_pool is not None
        servers[0]._apply_pool.quiesce = (
            lambda tok, timeout_s=0.0: False)  # a wedged shard
        with pytest.raises(log.CheckError, match="NOT committed"):
            cl.scheduler.snapshot(timeout_s=20.0)
        assert snap_mod.load_manifest(snapdir) is None
    finally:
        _kill(cl, servers)


def test_two_stores_share_directory_without_collision(tmp_path):
    """Two TieredStores in ONE process on one PS_STORE_DIR (in-process
    clusters) must not cross-corrupt segment files."""
    from pslite_tpu.kv.tiered import TieredStore
    from pslite_tpu.telemetry.metrics import Registry

    a = TieredStore(512, directory=str(tmp_path), shards=1,
                    metrics=Registry())
    b = TieredStore(512, directory=str(tmp_path), shards=1,
                    metrics=Registry())
    try:
        for st, base in ((a, 10.0), (b, 20.0)):
            for k in range(8):
                st[k] = np.full(128, base + k, np.float32)
                st.get(k)  # interleave appends into the shared dir
        for st, base in ((a, 10.0), (b, 20.0)):
            for k in range(8):
                assert np.array_equal(
                    st.get(k), np.full(128, base + k, np.float32)
                ), (base, k)
        a.close()  # must not unlink b's live segments
        for k in range(8):
            assert np.array_equal(
                b.get(k), np.full(128, 20.0 + k, np.float32))
    finally:
        a.close()
        b.close()


def test_manifest_age_and_slo_rule():
    """snapshot_age is a known PS_SLO rule, and manifest_age_s reports
    -1 (rule-skipped) for a never-snapshotted directory."""
    from pslite_tpu.telemetry.health import parse_slo

    rules = parse_slo("snapshot_age=5:50")
    assert rules["snapshot_age"].warn == 5.0
    assert rules["snapshot_age"].crit == 50.0
    assert rules["snapshot_age"].grade(10.0) == "warn"
    assert snap_mod.manifest_age_s("/nonexistent/nowhere") == -1.0
