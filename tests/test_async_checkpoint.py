"""AsyncEngineCheckpointer: snapshot-at-call semantics, restore parity,
and background-error surfacing."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from pslite_tpu.checkpoint import (
    AsyncEngineCheckpointer,
    restore_engine,
)
from pslite_tpu.parallel.engine import CollectiveEngine
from pslite_tpu.parallel.sparse import SparseEngine


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("kv",))


def test_async_snapshot_at_call_time(tmp_path):
    eng = CollectiveEngine(mesh=_mesh(), server_handle="sgd_momentum:0.1,0.9")
    keys = np.arange(4, dtype=np.uint64)
    eng.register_dense("b", keys, 64)
    eng.push_pull("b", np.ones((8, 256), np.float32))
    at_save = np.asarray(eng.store_array("b"))

    se = SparseEngine(eng.mesh)
    init = np.arange(20 * 4, dtype=np.float32).reshape(20, 4)
    se.register_sparse("t", 20, 4, init=init)

    ck = AsyncEngineCheckpointer()
    path = str(tmp_path / "snap")
    ck.save(eng, path, sparse_engine=se)
    # Mutations AFTER save() must not leak into the checkpoint.
    eng.push_pull("b", np.ones((8, 256), np.float32))
    ck.wait()

    eng2 = CollectiveEngine(mesh=_mesh(),
                            server_handle="sgd_momentum:0.1,0.9")
    eng2.register_dense("b", keys, 64)
    se2 = SparseEngine(eng2.mesh)
    se2.register_sparse("t", 20, 4)
    restore_engine(eng2, path, sparse_engine=se2)
    np.testing.assert_allclose(
        np.asarray(eng2.store_array("b")), at_save, rtol=1e-6
    )
    # Optimizer state restored: next step continues the momentum chain.
    kind, st = eng2.opt_state("b")
    assert kind == "sgd_momentum"
    got = np.asarray(se2.pull("t", np.broadcast_to(
        np.array([0, 7, 19], np.int32), (8, 3))))[0]
    np.testing.assert_allclose(got, init[[0, 7, 19]], rtol=1e-6)
    ck.close()


def test_async_error_surfaces(tmp_path):
    eng = CollectiveEngine(mesh=_mesh())
    eng.register_dense("b", np.arange(2, dtype=np.uint64), 16)
    blocker = tmp_path / "blocker"
    blocker.write_text("a file, not a dir")
    ck = AsyncEngineCheckpointer()
    ck.save(eng, str(blocker / "sub" / "snap"))
    with pytest.raises(Exception):
        ck.wait()
    # The checkpointer stays usable after a failure.
    ok = str(tmp_path / "ok")
    ck.save(eng, ok)
    ck.wait()
    eng2 = CollectiveEngine(mesh=_mesh())
    eng2.register_dense("b", np.arange(2, dtype=np.uint64), 16)
    restore_engine(eng2, ok)
    ck.close()


def test_server_handle_checkpoint_resume(tmp_path):
    """Async-PS server restart: snapshot the optimizer handle mid-stream,
    restore into a fresh handle, continue pushing — identical to an
    uninterrupted run (stateful kinds included)."""
    from pslite_tpu.checkpoint import load_server_handle, save_server_handle
    from pslite_tpu.kv.kv_app import KVMeta, KVPairs, KVServerOptimizerHandle

    class _Sink:
        def response(self, *a, **k):
            pass

    def push(h, key, grad):
        h(KVMeta(push=True),
          KVPairs(keys=np.array([key], np.uint64), vals=grad), _Sink())

    rng = np.random.default_rng(3)
    grads = [rng.normal(size=6).astype(np.float32) for _ in range(8)]

    for kind in ("sgd", "sgd_momentum", "adam"):
        ref = KVServerOptimizerHandle(kind=kind, lr=0.02)
        ref.init(4, np.ones(6, np.float32))
        for g in grads:
            push(ref, 4, g)

        first = KVServerOptimizerHandle(kind=kind, lr=0.02)
        first.init(4, np.ones(6, np.float32))
        for g in grads[:4]:
            push(first, 4, g)
        path = str(tmp_path / f"handle_{kind}")
        save_server_handle(first, path)

        resumed = KVServerOptimizerHandle(kind=kind, lr=0.02)
        load_server_handle(resumed, path)
        for g in grads[4:]:
            push(resumed, 4, g)
        np.testing.assert_allclose(
            resumed.store[4], ref.store[4], rtol=1e-6, atol=1e-7,
            err_msg=kind,
        )


def test_sparse_adagrad_acc_checkpoint_roundtrip(tmp_path):
    """save_engine/restore_engine carry the Adagrad accumulator: resumed
    training matches uninterrupted training (diverges if acc resets)."""
    from pslite_tpu.checkpoint import restore_engine, save_engine

    mesh = Mesh(np.array(jax.devices()[:4]), ("kv",))
    rng = np.random.default_rng(9)
    rows, dim = 13, 4
    init = rng.normal(size=(rows, dim)).astype(np.float32)
    idx = rng.integers(0, rows, size=(4, 3)).astype(np.int32)
    g1 = rng.normal(size=(4, 3, dim)).astype(np.float32)
    g2 = rng.normal(size=(4, 3, dim)).astype(np.float32)

    ref = SparseEngine(mesh)
    ref.register_sparse("t", rows, dim, init=init)
    ref.push("t", idx, g1, handle="row_adagrad:0.1")
    ref.push("t", idx, g2, handle="row_adagrad:0.1")
    all_idx = np.broadcast_to(np.arange(rows, dtype=np.int32), (4, rows))
    want = np.asarray(ref.pull("t", all_idx))[0]

    eng = CollectiveEngine(mesh=mesh)
    se1 = SparseEngine(mesh)
    se1.register_sparse("t", rows, dim, init=init)
    se1.push("t", idx, g1, handle="row_adagrad:0.1")
    path = str(tmp_path / "ck")
    save_engine(eng, path, sparse_engine=se1)

    se2 = SparseEngine(mesh)
    se2.register_sparse("t", rows, dim)
    restore_engine(CollectiveEngine(mesh=mesh), path, sparse_engine=se2)
    se2.push("t", idx, g2, handle="row_adagrad:0.1")
    got = np.asarray(se2.pull("t", all_idx))[0]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
