"""Serving fan-in (docs/batching.md): KVWorker.multi_get + the
server-side response combiner.

Covers the tentpole end to end over in-process loopback clusters —
multi-get bit-exactness vs sequential pulls across the codec ×
replication × PS_NATIVE × PS_BATCH_BYTES matrix, the one-frame-per-
server fan-out (submit_many), the one-handle/per-key-callback
completion contract, the hot-key cache partial-hit fast path with
read-your-writes, per-sub-op OPT_OVERLOAD sheds failing only the
affected keys, OPT_WRONG_OWNER bounces mid-multi-get re-slicing only
the bounced part, response aggregation of SEPARATE request frames,
the un-upgraded-sender capability gate, and psmon's resp ops/F
column.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from helpers import LoopbackCluster  # noqa: E402

from pslite_tpu.base import server_rank_to_id  # noqa: E402
from pslite_tpu.kv.batching import (  # noqa: E402
    OpCombiner,
    batchable,
    build_batch_message,
    split_batch_message,
)
from pslite_tpu.kv.hot_cache import HotKeyCache  # noqa: E402
from pslite_tpu.kv.kv_app import (  # noqa: E402
    KVServer,
    KVServerDefaultHandle,
    KVWorker,
    OverloadError,
)
from pslite_tpu.message import Message  # noqa: E402
from pslite_tpu.routing import RouteEntry, RoutingTable  # noqa: E402
from pslite_tpu.sarray import SArray  # noqa: E402


def _cluster(env_extra=None, num_servers=2, handle=None):
    cl = LoopbackCluster(num_workers=1, num_servers=num_servers,
                         env_extra={"PS_BATCH_BYTES": "65536",
                                    **(env_extra or {})})
    cl.start()
    servers = []
    for po in cl.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(handle() if handle else
                             KVServerDefaultHandle())
        servers.append(s)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    return cl, servers, w


def _teardown(cl, servers, w):
    w.stop()
    for s in servers:
        s.stop()
    cl.finalize()


def _spread_keys(n):
    span = (1 << 64) // n
    return np.arange(n, dtype=np.uint64) * np.uint64(span)


# -- bit-exactness matrix ----------------------------------------------------


@pytest.mark.parametrize("codec", [None, "int8"])
@pytest.mark.parametrize("replication", [1, 2])
@pytest.mark.parametrize("native", [0, 1])
@pytest.mark.parametrize("batch_bytes", [0, 65536])
def test_multi_get_matrix_bit_exact_vs_sequential(codec, replication,
                                                  native, batch_bytes):
    """multi_get returns byte-identical values to sequential pulls of
    the same keys, across wire codec, chain replication, the native
    plane toggle, and batching on/off."""
    env = {
        "PS_BATCH_BYTES": str(batch_bytes),
        "PS_NATIVE": str(native),
        "PS_KV_REPLICATION": str(replication),
        # EF folds each encode's residual into the NEXT encode of the
        # same slice (by design), so consecutive codec pulls are not
        # byte-identical; the matrix compares pure codec round trips.
        "PS_CODEC_EF": "0",
    }
    cl, servers, w = _cluster(env_extra=env)
    try:
        nk, vl = 32, 8
        keys = _spread_keys(nk)
        rng = np.random.default_rng(5)
        vals = rng.normal(size=nk * vl).astype(np.float32)
        w.wait(w.push(keys, vals))
        key_lists = [keys[i:i + 1] for i in range(nk)]
        kw = {"codec": codec} if codec else {}
        handle = w.multi_get(key_lists, val_len=vl, **kw)
        got = handle.wait()
        # Reference: sequential pulls, identical codec config.
        seq = np.zeros(vl, np.float32)
        for i in range(nk):
            w.wait(w.pull(keys[i:i + 1], seq, **kw))
            np.testing.assert_array_equal(got[i], seq)
        assert handle.errors == {}
    finally:
        _teardown(cl, servers, w)


def test_multi_get_one_frame_per_server_and_batched_response():
    """The fan-out's per-server slices enter the combiner atomically:
    ONE EXT_BATCH frame per contacted server, answered by ONE batched
    response frame per server (the ~1 RTT fan-in)."""
    cl, servers, w = _cluster(num_servers=2)
    try:
        nk, vl = 64, 8
        keys = _spread_keys(nk)
        vals = np.arange(nk * vl, dtype=np.float32)
        w.wait(w.push(keys, vals))
        # Warm capability so the fan-out below is fully batched.
        warm = np.zeros(vl, np.float32)
        w.wait(w.pull(keys[:1], warm))
        wvan = cl.workers[0].van
        f0, o0 = wvan._c_batched_frames.value, wvan._c_batch_ops.value
        r0 = [po.van._c_resp_batched_frames.value for po in cl.servers]
        handle = w.multi_get([keys[i:i + 1] for i in range(nk)],
                             val_len=vl)
        handle.wait()
        assert wvan._c_batched_frames.value - f0 == 2  # one per server
        assert wvan._c_batch_ops.value - o0 == nk
        for i, po in enumerate(cl.servers):
            assert po.van._c_resp_batched_frames.value - r0[i] == 1
        for i in range(nk):
            np.testing.assert_array_equal(
                handle.outs[i], vals[i * vl:(i + 1) * vl])
    finally:
        _teardown(cl, servers, w)


def test_multi_get_handle_and_callbacks():
    """One wait handle; per-sub-get callbacks fire as each completes;
    the aggregate callback fires once after the last success."""
    cl, servers, w = _cluster(num_servers=1)
    try:
        nk, vl = 8, 4
        keys = np.arange(nk, dtype=np.uint64)
        w.wait(w.push(keys, np.ones(nk * vl, np.float32)))
        fired = []
        done = threading.Event()
        cbs = [(lambda i=i: fired.append(i)) for i in range(nk)]
        handle = w.multi_get([keys[i:i + 1] for i in range(nk)],
                             val_len=vl, callbacks=cbs,
                             callback=done.set)
        handle.wait()
        assert done.wait(5.0)
        assert sorted(fired) == list(range(nk))
        assert len(handle) == nk
        # pull_multi is the bucket-flavored alias of the same path.
        h2 = w.pull_multi([keys[:2]], val_len=vl)
        h2.wait()
        np.testing.assert_array_equal(h2.outs[0],
                                      np.ones(2 * vl, np.float32))
    finally:
        _teardown(cl, servers, w)


# -- hot-key cache partial hits ----------------------------------------------


def test_multi_get_partial_cache_hit_fetches_only_misses():
    """Cached keys serve locally; only the misses ride the wire; the
    assembled buffer is bit-exact; fully-cached sub-gets send NO
    message and read-your-writes still holds after a push."""
    cl, servers, w = _cluster(num_servers=1,
                              env_extra={"PS_HOT_CACHE": "1"})
    try:
        nk, vl = 8, 4
        keys = np.arange(nk, dtype=np.uint64)
        vals = np.arange(nk * vl, dtype=np.float32)
        w.wait(w.push(keys, vals))
        # Warm the cache on the even keys only.
        o = np.zeros(vl, np.float32)
        for k in range(0, nk, 2):
            w.wait(w.pull(keys[k:k + 1], o))
        hits0 = w.po.metrics.counter("kv.hot_cache.hits").value
        handle = w.multi_get([keys], val_len=vl)
        handle.wait()
        np.testing.assert_array_equal(handle.outs[0], vals)
        assert w.po.metrics.counter(
            "kv.hot_cache.hits").value - hits0 == nk // 2
        # Fully-cached sub-gets: no timestamps, no wire traffic.
        sent0 = cl.workers[0].van._c_sent_msgs.value
        h2 = w.multi_get([keys[0:1], keys[2:3]], val_len=vl)
        h2.wait()
        assert h2.cached == 2 and h2.timestamps == [None, None]
        assert cl.workers[0].van._c_sent_msgs.value == sent0
        np.testing.assert_array_equal(h2.outs[0], vals[0:vl])
        # Read-your-writes: a push invalidates the fill; the next
        # multi_get must fetch fresh values, not the stale cache.
        w.wait(w.push(keys[0:1], np.full(vl, 50.0, np.float32)))
        h3 = w.multi_get([keys[0:1]], val_len=vl)
        h3.wait()
        assert h3.cached == 0
        np.testing.assert_array_equal(h3.outs[0], vals[0:vl] + 50.0)
    finally:
        _teardown(cl, servers, w)


def test_serve_mask_unit_validity_rules():
    """serve_mask's validity is serve()'s: stale-stamp entries count
    misses and drop (the fill-race guard), shape mismatches decline
    wholesale, live rows copy in place."""
    c = HotKeyCache(max_bytes=1 << 20, ttl_s=30.0)
    keys = np.arange(4, dtype=np.uint64)
    c.fill(8, 5, keys[:2], np.arange(8, dtype=np.float32))  # keys 0,1
    out = np.zeros(16, np.float32)
    mask = c.serve_mask(keys, out)
    assert list(mask) == [True, True, False, False]
    np.testing.assert_array_equal(out[:8],
                                  np.arange(8, dtype=np.float32))
    # A newer observed stamp invalidates the fills: all misses now.
    c.observe(8, 9)
    out2 = np.zeros(16, np.float32)
    assert not c.serve_mask(keys, out2).any()
    assert len(c) == 0  # dropped on probe, like serve()
    # Fill-race: a fill older than the known stamp is skipped at fill
    # time, so serve_mask can never resurrect it.
    c.fill(8, 7, keys[:1], np.ones(4, np.float32))
    assert len(c) == 0
    # Non-partitionable buffer: declined wholesale, nothing touched.
    c.fill(8, 11, keys[:1], np.ones(4, np.float32))
    assert c.serve_mask(keys, np.zeros(7, np.float32)) is None


# -- per-sub-op failure isolation --------------------------------------------


def test_multi_get_overload_sheds_fail_only_affected_subs():
    """Per-tenant admission through a multi-get fan-out sheds SUB-OPS:
    the shed sub-gets' waits raise OverloadError and their callbacks
    are suppressed; siblings complete bit-exact."""
    cl, servers, w = _cluster(num_servers=1, env_extra={
        "PS_TENANTS": "serve:8,train:1",
        "PS_TENANT_QUEUE_LIMIT": "4",
        "PS_BATCH_NEGOTIATE": "0",
    })
    try:
        nk, vl = 64, 256
        keys = np.arange(nk, dtype=np.uint64)
        vals = np.ones(nk * vl, np.float32)
        while True:
            try:
                w.wait(w.push(keys, vals, tenant="train"))
                break
            except OverloadError:
                time.sleep(0.01)
        fired = []
        cbs = [(lambda i=i: fired.append(i)) for i in range(nk)]
        handle = w.multi_get([keys[i:i + 1] for i in range(nk)],
                             val_len=vl, tenant="train",
                             callbacks=cbs)
        with pytest.raises(OverloadError):
            handle.wait()
        shed = set(handle.errors)
        assert shed  # the tiny limit must have shed something
        assert all(isinstance(e, OverloadError)
                   for e in handle.errors.values())
        # Siblings completed bit-exact; their callbacks fired; the
        # shed sub-gets' callbacks were suppressed.
        for i in range(nk):
            if i in shed:
                assert i not in fired
            else:
                assert i in fired
                np.testing.assert_array_equal(
                    handle.outs[i], vals[i * vl:(i + 1) * vl])
    finally:
        _teardown(cl, servers, w)


# -- elastic: wrong-owner bounce mid-multi-get --------------------------------


def test_multi_get_wrong_owner_reslices_only_bounced_subs():
    """A stale worker's multi-get spans both servers; a doctored newer
    epoch flips rank 1's ranges to rank 0.  Only the bounced sub-gets
    re-route (rank 0's answer directly); every wait completes and all
    values land bit-exact."""
    cl = LoopbackCluster(num_workers=1, num_servers=2, env_extra={
        "PS_ELASTIC": "1",
        "PS_REQUEST_TIMEOUT": "2.0",
        "PS_REQUEST_RETRIES": "8",
    })
    cl.start()
    servers = []
    for po in cl.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    try:
        nk, vl = 8, 4
        keys = _spread_keys(nk) + np.uint64(7)
        vals = np.arange(nk * vl, dtype=np.float32)
        w.wait(w.push(keys, vals))
        base = cl.scheduler.routing_table()
        doctored = RoutingTable(
            epoch=base.epoch + 1, num_servers=2, active=(0, 1),
            entries=tuple(
                RouteEntry(e.begin, e.end,
                           0 if e.owner == 1 else e.owner)
                for e in base.entries
            ),
        )
        r0 = next(s for s in servers
                  if s.po.van.my_node.id == server_rank_to_id(0))
        r1 = next(s for s in servers
                  if s.po.van.my_node.id == server_rank_to_id(1))
        for k, v in list(r1._handle.store.items()):
            r0._handle.store[k] = v.copy()
        cl.scheduler.apply_routing(doctored)
        for s in (r0, r1):
            s.po.apply_routing(doctored)
        # The worker still slices under the OLD epoch: rank-1 sub-gets
        # bounce and re-route; rank-0 sub-gets answer directly.
        bounced0 = w.po.metrics.counter("kv.wrong_owner_bounces").value
        p0 = r0._c_pull_reqs.value
        handle = w.multi_get([keys[i:i + 1] for i in range(nk)],
                             val_len=vl)
        handle.wait()
        assert handle.errors == {}
        for i in range(nk):
            np.testing.assert_array_equal(
                handle.outs[i], vals[i * vl:(i + 1) * vl])
        bounced = (w.po.metrics.counter("kv.wrong_owner_bounces").value
                   - bounced0)
        assert bounced >= 1  # the rank-1 half re-routed ...
        assert r1._c_wrong_owner.value >= 1
        # ... and ONLY that half: rank 0 saw exactly one pull per
        # sub-get (its own half directly + the re-routed half), never
        # a duplicate from an unbounced sub-get re-slicing.
        assert r0._c_pull_reqs.value - p0 == nk
    finally:
        for ww in [w]:
            ww.stop()
        for s in servers:
            s.stop()
        for po in cl.all_nodes():
            try:
                po.van.stop()
            except Exception:  # noqa: BLE001 - already stopped
                pass


# -- response aggregation of separate frames ---------------------------------


def test_separate_frames_aggregate_responses():
    """Requests too large to merge on the request side (tiny
    PS_BATCH_BYTES) still get their RESPONSES aggregated: the server's
    response combiner coalesces acks of separate frames toward the
    probed sender, the store stays bit-exact, and the response
    counters land on the resp-direction ledger."""
    cl, servers, w = _cluster(num_servers=1, env_extra={
        "PS_BATCH_BYTES": "64",
        "PS_RESP_BATCH_BYTES": "65536",
    })
    try:
        keys = np.array([3], np.uint64)
        w.wait(w.push(keys, np.ones(64, np.float32)))  # probe warms
        tss = [w.push(keys, np.ones(64, np.float32)) for _ in range(80)]
        for ts in tss:
            w.wait(ts)
        out = np.zeros(64, np.float32)
        w.wait(w.pull(keys, out))
        np.testing.assert_array_equal(out, np.full(64, 81.0, np.float32))
        wvan, svan = cl.workers[0].van, cl.servers[0].van
        assert wvan._c_batched_frames.value == 0  # nothing merged out
        assert svan._c_resp_batched_frames.value > 0
        assert (svan._c_resp_batch_ops.value
                > svan._c_resp_batched_frames.value)
    finally:
        _teardown(cl, servers, w)


def test_unproved_sender_never_sees_aggregated_response():
    """Capability gate: a worker that never probed and never sent an
    EXT_BATCH frame (batching off) gets ONLY plain responses, even
    with the server's response combiner explicitly on."""
    cl = LoopbackCluster(num_workers=1, num_servers=1, env_extra={
        "PS_BATCH_BYTES": "0",
        "PS_RESP_BATCH_BYTES": "65536",
    })
    cl.start()
    s = KVServer(0, postoffice=cl.servers[0])
    s.set_request_handle(KVServerDefaultHandle())
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    try:
        assert s._resp_combiner is not None  # plane on server-side
        keys = np.array([1], np.uint64)
        tss = [w.push(keys, np.ones(8, np.float32)) for _ in range(40)]
        for ts in tss:
            w.wait(ts)
        out = np.zeros(8, np.float32)
        w.wait(w.pull(keys, out))
        np.testing.assert_array_equal(out, np.full(8, 40.0, np.float32))
        assert cl.servers[0].van._c_resp_batched_frames.value == 0
        assert not s._batch_senders
    finally:
        w.stop()
        s.stop()
        cl.finalize()


def test_response_combiner_carries_option_and_stamp():
    """Unit: response-direction build/split round-trips per-op result
    codes and hot-cache stamps, and response-mode batchable accepts
    empty-data acks while declining error-marked frames (they ride as
    singles in position)."""

    def _resp(ts, key, stamp=0, option=0, data=True):
        msg = Message()
        m = msg.meta
        m.head = 0
        m.request = False
        m.push = not data
        m.pull = data
        m.timestamp = ts
        m.key = key
        m.recver = 9
        m.stamp = stamp
        m.option = option
        if data:
            msg.add_data(SArray(np.array([key], np.uint64)))
            msg.add_data(SArray(np.ones(4, np.float32)))
        return msg

    a = _resp(1, 10, stamp=7)
    b = _resp(2, 11, stamp=8)
    ack = _resp(3, 12, data=False)
    err = _resp(4, 13, option=3)
    assert batchable(a, response=True)
    assert batchable(ack, response=True)  # empty-data ack merges
    assert not batchable(err, response=True)  # option != 0: single
    assert not batchable(a)  # request-direction check still strict
    env = build_batch_message([a, b, ack])
    assert env.meta.request is False
    subs = split_batch_message(env)
    assert [s.meta.stamp for s in subs] == [7, 8, 0]
    assert [s.meta.timestamp for s in subs] == [1, 2, 3]
    assert len(subs[2].data) == 0
    np.testing.assert_array_equal(subs[0].data[1].numpy(),
                                  np.ones(4, np.float32))
    # An OpCombiner in response mode emits [batch(3), err single] for
    # the run above — order preserved, error as a single in position.
    sent = []
    c = OpCombiner(lambda m: sent.append(m) or 0,
                   lambda msgs, exc: None, max_bytes=1 << 20,
                   response=True)
    c._flush([(a, 16, True), (b, 16, True), (ack, 0, True),
              (err, 16, False)])
    shapes = [len(m.meta.batch.ops) if m.meta.batch else 1 for m in sent]
    assert shapes == [3, 1]
    assert sent[1] is err


def test_submit_many_flushes_whole_fanout_immediately():
    """submit_many marks every touched lane flush-ready: the whole
    fan-out leaves as one frame per lane at the next pickup, with no
    adaptive hold."""
    sent = []
    done = threading.Event()

    def send(m):
        sent.append(m)
        if len(sent) >= 2:
            done.set()
        return 0

    c = OpCombiner(send, lambda msgs, exc: None, max_bytes=1 << 20,
                   min_ops=1000, hold_max_us=2_000_000)
    msgs = []
    for dest in (8, 10):
        for i in range(5):
            msg = Message()
            m = msg.meta
            m.request = True
            m.timestamp = dest * 100 + i
            m.key = i
            m.head = 0
            m.push = True
            m.recver = dest
            msg.add_data(SArray(np.array([i], np.uint64)))
            msg.add_data(SArray(np.ones(4, np.float32)))
            msgs.append(msg)
    c.submit_many(msgs)
    assert done.wait(5.0)  # flushed despite min_ops=1000 / 2s hold
    assert len(sent) == 2
    assert sorted(len(m.meta.batch.ops) for m in sent) == [5, 5]
    c.stop()


def test_psmon_resp_ops_per_frame_column():
    """psmon renders the response-direction aggregation column from
    the server-origin van counters."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import psmon

    snap = {
        8: {"role": "server", "metrics": {
            "uptime_s": 5.0,
            "counters": {"van.resp_batched_frames": 4,
                         "van.resp_batch_ops": 128},
        }},
        9: {"role": "worker", "metrics": {
            "uptime_s": 5.0,
            "counters": {"van.batched_frames": 2,
                         "van.batch_ops": 64},
        }},
    }
    table = psmon.format_table(snap)
    assert "resp ops/F" in table
    assert "32.0" in table  # 128 / 4 on the server row
    # The resp ops/F cell sits 4th from the row's end (the tiered-
    # store ram/cold + cold% cells and the read% share land after it
    # — columns ride LAST in landing order, so parse relative to the
    # column, not the line tail).
    server_rows = [line.split() for line in table.splitlines()
                   if " server " in f" {line} "]
    assert server_rows and server_rows[0][-4] == "32.0"
