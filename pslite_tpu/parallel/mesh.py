"""Device mesh construction.

The PS roles map onto mesh axes instead of RDMA endpoints (SURVEY §2.9):
the ``kv`` axis carries both the worker fan-in (gradient reduction) and the
server sharding (key-range ownership) — the JOINT/colocated deployment of
the reference (``ps.h:59-76``), which is the natural fit for a TPU slice.
Model-parallel axes (dp/sp/tp) for the model zoo are built with
:func:`make_mesh`.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def default_mesh(axis_name: str = "kv", num_devices: Optional[int] = None):
    """1-D mesh over all (or the first ``num_devices``) local devices."""
    import jax

    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    from jax.sharding import Mesh

    return Mesh(np.asarray(devices), (axis_name,))


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions, with the replication check off
    (collective outputs like tiled all_gather are replicated by
    construction; the static checker cannot always infer that)."""
    import jax

    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )
        except TypeError:  # older signature
            return jax.shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
            )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def make_mesh(shape: Sequence[int], axis_names: Tuple[str, ...]):
    """N-D mesh with the given per-axis sizes (product must divide the
    available device count)."""
    import jax
    from jax.sharding import Mesh

    n = int(np.prod(shape))
    devices = np.asarray(jax.devices()[:n]).reshape(shape)
    return Mesh(devices, tuple(axis_names))
