"""Interface/IP discovery and ephemeral-port picking.

Equivalent of the reference's ``src/network_utils.h`` (``GetIP``,
``GetAvailableInterfaceAndIP``, ``GetAvailablePort``).
"""

from __future__ import annotations

import socket
from typing import Optional


def get_ip(interface: Optional[str] = None) -> str:
    """Best-effort local IP discovery.

    Without netlink access we use the UDP-connect trick; for an explicit
    interface name we fall back to hostname resolution.  Matches the
    reference's behavior of preferring a non-loopback address.
    """
    if interface == "lo":
        return "127.0.0.1"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def get_available_port(host: str = "") -> int:
    """Bind port 0 and return the kernel-assigned ephemeral port."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()
