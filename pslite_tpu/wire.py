"""Wire format: Meta <-> bytes, and message framing for byte-stream vans.

Equivalent of the reference's hand-rolled POD wire format
(``src/meta.h``, ``PackMeta/UnpackMeta/GetPackMetaLen`` in
``src/van.cc:689-831``) — a compact little-endian layout, no protobuf.
The layout here is our own (versioned, explicit field order); when the native
C++ core is built it implements this exact format so Python and C++ peers
interoperate.

Frame layout used by stream transports (tcp van)::

    u32 magic | u32 meta_len | u32 n_data | u64 data_len[n_data] | meta | data...
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

from .message import (
    BatchInfo,
    BatchOp,
    ChunkInfo,
    CodecInfo,
    Command,
    Control,
    Message,
    Meta,
    Node,
    Role,
    code_dtype,
)
from .sarray import SArray

MAGIC = 0x50535450  # "PSTP"
WIRE_VERSION = 2  # v2: priority field (send scheduling echo)

# Optional trailing extension blocks appended after the node list:
# ``u8 tag | u8 len | payload[len]``.  Decoders skip unknown tags by
# length and older decoders (which stop after the node list) ignore the
# tail entirely, so extensions never bump WIRE_VERSION — the native C++
# core frames meta as opaque bytes and is unaffected.
_EXT_HDR = struct.Struct("<BB")
EXT_TRACE = 1  # payload: u64 trace id (telemetry/tracing.py)
_EXT_TRACE_PAYLOAD = struct.Struct("<Q")
# Chunked streaming transfer (docs/chunking.md): xfer id, chunk index,
# chunk count, byte offset, then the original segment table (u64 len +
# u8 dtype code per segment) so any chunk can seed reassembly.  The
# u8 ext length bounds the table at _CHUNK_MAX_SEGS segments — the van
# only chunks messages within that bound.
EXT_CHUNK = 2
_EXT_CHUNK_FIXED = struct.Struct("<QIIQB")  # xfer index total offset nseg
_EXT_CHUNK_SEG = struct.Struct("<QB")       # seg byte len, dtype code
CHUNK_MAX_SEGS = (255 - _EXT_CHUNK_FIXED.size) // _EXT_CHUNK_SEG.size
# Wire compression (docs/compression.md): codec id, flag bits, scale
# block length (elements), uncompressed payload byte count.  ALWAYS
# packed before EXT_CHUNK: the native chunk splitter patches the meta's
# trailing bytes as the chunk extension, so EXT_CHUNK must stay last.
EXT_CODEC = 3
_EXT_CODEC_PAYLOAD = struct.Struct("<BBHQ")  # codec flags block raw_len
# Multi-tenant QoS (docs/qos.md): tenant id + server push-version
# stamp.  Packed (only when either is nonzero) BEFORE EXT_CODEC /
# EXT_CHUNK, preserving the invariant that EXT_CHUNK stays the meta's
# trailing bytes (the native splitter's patch contract).
EXT_QOS = 4
_EXT_QOS_PAYLOAD = struct.Struct("<HQ")  # tenant, stamp
# Small-op aggregation (docs/batching.md): this frame carries N
# independent KV ops.  The u8 ext length cannot hold a per-op table,
# so the payload is just (n_ops, table_len) and the table itself is
# serialized AHEAD of ``meta.body`` (stripped again at unpack — body
# round-trips unchanged).  Packed before EXT_CODEC/EXT_CHUNK so
# EXT_CHUNK stays the trailing bytes (the native splitter's contract).
# Used in BOTH directions with one layout: request frames (worker op
# combiner; per-op option/stamp always 0) and response frames (batched
# group responses + the server's response combiner; per-op option
# carries OPT_APPLY_ERROR/OPT_OVERLOAD result codes, per-op stamp the
# hot-cache push-version).  Capability-gated both ways: senders only
# emit EXT_BATCH toward peers that answered the batch probe, and
# servers only aggregate responses toward senders that probed (or sent
# an EXT_BATCH frame) — old decoders never see these frames.
EXT_BATCH = 5
_EXT_BATCH_PAYLOAD = struct.Struct("<HI")  # n_ops, table_len
_BATCH_OP_FIXED = struct.Struct("<BBiQqqQ")
# flags, nseg, timestamp, key, val_len, option, stamp
# Per-op trace id (telemetry/tracing.py): a u64 appended AFTER the
# codec block when the flag is set — untraced ops (and therefore whole
# untraced frames) stay byte-identical to pre-trace builds.  The
# addition is capability-gated by BATCH_WIRE_VERSION (kv/batching.py):
# peers answering an older version never receive EXT_BATCH frames.
_BATCH_F_PUSH, _BATCH_F_PULL, _BATCH_F_CODEC, _BATCH_F_TRACE = 1, 2, 4, 8
BATCH_MAX_OPS = 0xFFFF  # u16 op count


def _pack_batch_table(info: BatchInfo) -> bytes:
    parts = []
    for op in info.ops:
        flags = (
            (_BATCH_F_PUSH if op.push else 0)
            | (_BATCH_F_PULL if op.pull else 0)
            | (_BATCH_F_CODEC if op.codec is not None else 0)
            | (_BATCH_F_TRACE if op.trace else 0)
        )
        parts.append(_BATCH_OP_FIXED.pack(
            flags, op.nseg & 0xFF, op.timestamp, op.key % (1 << 64),
            op.val_len, op.option, op.stamp % (1 << 64),
        ))
        if op.codec is not None:
            cd = op.codec
            parts.append(_EXT_CODEC_PAYLOAD.pack(
                cd.codec & 0xFF, cd.flags & 0xFF, cd.block & 0xFFFF,
                cd.raw_len % (1 << 64),
            ))
        if op.trace:
            parts.append(_EXT_TRACE_PAYLOAD.pack(op.trace % (1 << 64)))
    return b"".join(parts)


def _unpack_batch_table(table: memoryview, n_ops: int) -> BatchInfo:
    ops = []
    off = 0
    for _ in range(n_ops):
        flags, nseg, ts, key, val_len, option, stamp = (
            _BATCH_OP_FIXED.unpack_from(table, off)
        )
        off += _BATCH_OP_FIXED.size
        codec = None
        if flags & _BATCH_F_CODEC:
            c_id, c_flags, c_block, c_raw = _EXT_CODEC_PAYLOAD.unpack_from(
                table, off
            )
            off += _EXT_CODEC_PAYLOAD.size
            codec = CodecInfo(codec=c_id, raw_len=c_raw, block=c_block,
                              flags=c_flags)
        trace = 0
        if flags & _BATCH_F_TRACE:
            (trace,) = _EXT_TRACE_PAYLOAD.unpack_from(table, off)
            off += _EXT_TRACE_PAYLOAD.size
        ops.append(BatchOp(
            push=bool(flags & _BATCH_F_PUSH),
            pull=bool(flags & _BATCH_F_PULL),
            timestamp=ts, key=key, val_len=val_len, option=option,
            stamp=stamp, nseg=nseg, codec=codec, trace=trace,
        ))
    return BatchInfo(ops=tuple(ops))

_META_FIXED = struct.Struct(
    "<B"  # version
    "iiiii i"  # head app_id customer_id timestamp sender recver
    "B"  # flags: request|push|pull|simple_app
    "Q Q q q i q i"  # key addr val_len option sid data_size priority
    "b i b i"  # src_dev_type src_dev_id dst_dev_type dst_dev_id
    "B i Q"  # control_cmd barrier_group msg_sig
    "H H I"  # num_nodes num_data_types body_len
)

# Fixed byte offsets inside _META_FIXED consumed by the native core
# (cpp/pslite_core.cc): the sender lanes stamp ``sid`` at transmit time
# and patch the chunk extension per chunk, the express receive lane
# peeks ``priority``/``control_cmd``.  Asserted against the struct
# layout in tests/test_wire.py — keep in sync with the kMeta* constants
# in pslite_core.cc.
META_SID_OFF = 58
META_PRIORITY_OFF = 70
META_CONTROL_CMD_OFF = 84
META_FIXED_SIZE = _META_FIXED.size  # 105


def chunk_ext_payload_size(nseg: int) -> int:
    """Byte length of an EXT_CHUNK payload with ``nseg`` segments —
    the native chunk splitter locates the extension as the trailing
    ``payload`` bytes of the packed meta (pack_meta appends it last)."""
    return _EXT_CHUNK_FIXED.size + nseg * _EXT_CHUNK_SEG.size

_NODE_FIXED = struct.Struct("<B i i B i H H H H")  # role id customer_id
# is_recovery aux_id hostname_len num_ports num_devs endpoint_len

_F_REQUEST, _F_PUSH, _F_PULL, _F_SIMPLE, _F_SHM = 1, 2, 4, 8, 16


def _pack_node(n: Node) -> bytes:
    host = n.hostname.encode()
    ndev = len(n.dev_types)
    out = [
        _NODE_FIXED.pack(
            int(n.role),
            n.id,
            n.customer_id,
            int(n.is_recovery),
            n.aux_id,
            len(host),
            len(n.ports),
            ndev,
            len(n.endpoint_name),
        ),
        host,
        struct.pack(f"<{len(n.ports)}i", *n.ports),
        struct.pack(f"<{ndev}i", *n.dev_types),
        struct.pack(f"<{ndev}i", *n.dev_ids),
        bytes(n.endpoint_name),
    ]
    return b"".join(out)


def _unpack_node(buf: memoryview, off: int) -> Tuple[Node, int]:
    (role, nid, cust, is_rec, aux, hlen, nports, ndev, elen) = _NODE_FIXED.unpack_from(
        buf, off
    )
    off += _NODE_FIXED.size
    host = bytes(buf[off : off + hlen]).decode()
    off += hlen
    ports = list(struct.unpack_from(f"<{nports}i", buf, off))
    off += 4 * nports
    dev_types = list(struct.unpack_from(f"<{ndev}i", buf, off))
    off += 4 * ndev
    dev_ids = list(struct.unpack_from(f"<{ndev}i", buf, off))
    off += 4 * ndev
    endpoint = bytes(buf[off : off + elen])
    off += elen
    node = Node(
        role=Role(role),
        id=nid,
        customer_id=cust,
        hostname=host,
        ports=ports,
        dev_types=dev_types,
        dev_ids=dev_ids,
        is_recovery=bool(is_rec),
        endpoint_name=endpoint,
        aux_id=aux,
    )
    return node, off


def pack_meta(meta: Meta) -> bytes:
    flags = (
        (_F_REQUEST if meta.request else 0)
        | (_F_PUSH if meta.push else 0)
        | (_F_PULL if meta.pull else 0)
        | (_F_SIMPLE if meta.simple_app else 0)
        | (_F_SHM if meta.shm_data else 0)
    )
    ctrl = meta.control
    # Small-op aggregation (docs/batching.md): the per-op table rides
    # ahead of the caller's body bytes; EXT_BATCH records (n_ops,
    # table_len) so the decoder strips it back out — meta.body itself
    # round-trips unchanged.
    body = bytes(meta.body)
    batch_table = b""
    if meta.batch is not None:
        batch_table = _pack_batch_table(meta.batch)
        body = batch_table + body
    fixed = _META_FIXED.pack(
        WIRE_VERSION,
        meta.head,
        meta.app_id,
        meta.customer_id,
        meta.timestamp,
        meta.sender,
        meta.recver,
        flags,
        meta.key % (1 << 64),
        meta.addr % (1 << 64),
        meta.val_len,
        meta.option,
        meta.sid,
        meta.data_size,
        meta.priority,
        meta.src_dev_type,
        meta.src_dev_id,
        meta.dst_dev_type,
        meta.dst_dev_id,
        int(ctrl.cmd),
        ctrl.barrier_group,
        ctrl.msg_sig % (1 << 64),
        len(ctrl.node),
        len(meta.data_type),
        len(body),
    )
    parts = [fixed]
    parts.append(bytes(bytearray(min(c, 255) for c in meta.data_type)))
    parts.append(body)
    for n in ctrl.node:
        parts.append(_pack_node(n))
    if meta.trace:
        parts.append(_EXT_HDR.pack(EXT_TRACE, _EXT_TRACE_PAYLOAD.size))
        parts.append(_EXT_TRACE_PAYLOAD.pack(meta.trace % (1 << 64)))
    if meta.tenant or meta.stamp:
        parts.append(_EXT_HDR.pack(EXT_QOS, _EXT_QOS_PAYLOAD.size))
        parts.append(_EXT_QOS_PAYLOAD.pack(
            meta.tenant & 0xFFFF, meta.stamp % (1 << 64),
        ))
    if meta.batch is not None:
        parts.append(_EXT_HDR.pack(EXT_BATCH, _EXT_BATCH_PAYLOAD.size))
        parts.append(_EXT_BATCH_PAYLOAD.pack(
            len(meta.batch.ops) & 0xFFFF, len(batch_table),
        ))
    if meta.codec is not None:
        cd = meta.codec
        parts.append(_EXT_HDR.pack(EXT_CODEC, _EXT_CODEC_PAYLOAD.size))
        parts.append(_EXT_CODEC_PAYLOAD.pack(
            cd.codec & 0xFF, cd.flags & 0xFF, cd.block & 0xFFFF,
            cd.raw_len % (1 << 64),
        ))
    if meta.chunk is not None:
        ck = meta.chunk
        nseg = len(ck.seg_lens)
        payload = [_EXT_CHUNK_FIXED.pack(
            ck.xfer % (1 << 64), ck.index, ck.total, ck.offset, nseg,
        )]
        for ln, code in zip(ck.seg_lens, ck.seg_types):
            payload.append(_EXT_CHUNK_SEG.pack(int(ln), int(code)))
        blob = b"".join(payload)
        parts.append(_EXT_HDR.pack(EXT_CHUNK, len(blob)))
        parts.append(blob)
    return b"".join(parts)


def unpack_meta(buf: bytes) -> Meta:
    view = memoryview(buf)
    fields = _META_FIXED.unpack_from(view, 0)
    (
        version,
        head,
        app_id,
        customer_id,
        timestamp,
        sender,
        recver,
        flags,
        key,
        addr,
        val_len,
        option,
        sid,
        data_size,
        priority,
        src_dt,
        src_di,
        dst_dt,
        dst_di,
        ctrl_cmd,
        barrier_group,
        msg_sig,
        num_nodes,
        num_dtypes,
        body_len,
    ) = fields
    if version != WIRE_VERSION:
        raise ValueError(f"wire version mismatch: {version} != {WIRE_VERSION}")
    off = _META_FIXED.size
    data_type = list(view[off : off + num_dtypes])
    off += num_dtypes
    body = bytes(view[off : off + body_len])
    off += body_len
    nodes = []
    for _ in range(num_nodes):
        node, off = _unpack_node(view, off)
        nodes.append(node)
    trace = 0
    chunk = None
    codec = None
    batch = None
    tenant = 0
    stamp = 0
    while off + _EXT_HDR.size <= len(view):
        tag, ext_len = _EXT_HDR.unpack_from(view, off)
        off += _EXT_HDR.size
        if off + ext_len > len(view):
            break  # truncated tail: ignore, extensions are optional
        if tag == EXT_TRACE and ext_len == _EXT_TRACE_PAYLOAD.size:
            (trace,) = _EXT_TRACE_PAYLOAD.unpack_from(view, off)
        elif tag == EXT_QOS and ext_len == _EXT_QOS_PAYLOAD.size:
            tenant, stamp = _EXT_QOS_PAYLOAD.unpack_from(view, off)
        elif tag == EXT_BATCH and ext_len == _EXT_BATCH_PAYLOAD.size:
            n_ops, table_len = _EXT_BATCH_PAYLOAD.unpack_from(view, off)
            # The per-op table rode ahead of the caller's body bytes
            # (see pack_meta): strip it back out so body round-trips.
            if table_len <= len(body):
                batch = _unpack_batch_table(
                    memoryview(body)[:table_len], n_ops
                )
                body = body[table_len:]
        elif tag == EXT_CODEC and ext_len == _EXT_CODEC_PAYLOAD.size:
            c_id, c_flags, c_block, c_raw = _EXT_CODEC_PAYLOAD.unpack_from(
                view, off
            )
            codec = CodecInfo(codec=c_id, raw_len=c_raw, block=c_block,
                              flags=c_flags)
        elif tag == EXT_CHUNK and ext_len >= _EXT_CHUNK_FIXED.size:
            xfer, index, total, c_off, nseg = _EXT_CHUNK_FIXED.unpack_from(
                view, off
            )
            if ext_len == _EXT_CHUNK_FIXED.size + nseg * _EXT_CHUNK_SEG.size:
                so = off + _EXT_CHUNK_FIXED.size
                seg_lens, seg_types = [], []
                for _ in range(nseg):
                    ln, code = _EXT_CHUNK_SEG.unpack_from(view, so)
                    so += _EXT_CHUNK_SEG.size
                    seg_lens.append(ln)
                    seg_types.append(code)
                chunk = ChunkInfo(
                    xfer=xfer, index=index, total=total, offset=c_off,
                    seg_lens=tuple(seg_lens), seg_types=tuple(seg_types),
                )
        off += ext_len  # unknown tags skip by length
    meta = Meta(
        head=head,
        app_id=app_id,
        customer_id=customer_id,
        timestamp=timestamp,
        sender=sender,
        recver=recver,
        request=bool(flags & _F_REQUEST),
        push=bool(flags & _F_PUSH),
        pull=bool(flags & _F_PULL),
        simple_app=bool(flags & _F_SIMPLE),
        shm_data=bool(flags & _F_SHM),
        body=body,
        data_type=data_type,
        control=Control(
            cmd=Command(ctrl_cmd), node=nodes, barrier_group=barrier_group,
            msg_sig=msg_sig,
        ),
        key=key,
        addr=addr,
        val_len=val_len,
        option=option,
        sid=sid,
        data_size=data_size,
        priority=priority,
        trace=trace,
        chunk=chunk,
        codec=codec,
        batch=batch,
        tenant=tenant,
        stamp=stamp,
        src_dev_type=src_dt,
        src_dev_id=src_di,
        dst_dev_type=dst_dt,
        dst_dev_id=dst_di,
    )
    return meta


# -- stream framing ----------------------------------------------------------

_FRAME_HDR = struct.Struct("<III")  # magic, meta_len, n_data


def pack_frame(msg: Message) -> List[bytes]:
    """Serialize a message into an iovec-style list of byte chunks.

    Data segments are passed through zero-copy (memoryviews over the numpy
    buffers) so large tensors are never copied on the send path.
    """
    meta_buf = pack_meta(msg.meta)
    lens = struct.pack(f"<{len(msg.data)}Q", *[d.nbytes for d in msg.data])
    hdr = _FRAME_HDR.pack(MAGIC, len(meta_buf), len(msg.data))
    chunks: List[bytes] = [hdr, lens, meta_buf]
    for d in msg.data:
        arr = d.data
        # Fast path: already-contiguous arrays (the overwhelmingly
        # common case — every KVPairs slice is) go straight to a
        # memoryview; ascontiguousarray is reserved for the rare
        # strided view, where it actually has to copy.
        if not (isinstance(arr, np.ndarray) and arr.flags["C_CONTIGUOUS"]):
            arr = np.ascontiguousarray(arr)
        chunks.append(memoryview(arr).cast("B"))
    return chunks


def unpack_frame_header(hdr: bytes) -> Tuple[int, int]:
    magic, meta_len, n_data = _FRAME_HDR.unpack(hdr)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic: {magic:#x}")
    return meta_len, n_data


FRAME_HEADER_SIZE = _FRAME_HDR.size


def rebuild_message(meta: Meta, data_bufs: List[bytes]) -> Message:
    """Reassemble a Message from unpacked meta + raw data segments.

    Segments may be bytes-like (frombuffer view) or uint8 ndarrays (the
    tcp van's pooled receive arena — a .view keeps every derived array's
    ``base`` collapsed onto the pool-owned block, which is what lets the
    pool's refcount probe prove the block is free again).
    """
    msg = Message(meta=meta)
    for i, raw in enumerate(data_bufs):
        code = meta.data_type[i] if i < len(meta.data_type) else 2
        if isinstance(raw, np.ndarray):
            arr = raw.view(code_dtype(code))
        else:
            arr = np.frombuffer(raw, dtype=code_dtype(code))
        msg.data.append(SArray(arr))
    return msg
