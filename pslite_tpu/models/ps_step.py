"""Shared PS training cycle over a flat parameter store.

Every model family trains through the same four-phase SPMD program
(SURVEY §7 / docs/overview.md):

    pull    params = all_gather(store_shards)        # over ALL mesh axes
    compute loss, grads = value_and_grad(local_loss)
    push    agg = psum_scatter(flat_grads)           # cross-worker sum
    update  store_shard -= lr * agg / num_devices    # mean-gradient SGD

This module is that cycle, written once: the transformer (dp x sp mesh,
ring attention / TP / EP inside ``local_loss``) and the CNN (1-D dp mesh)
both build on it, so the padding math, mean scaling, donation, and
sharding specs cannot drift apart.
"""

from __future__ import annotations

from typing import Callable, Sequence


def make_flat_ps_step(
    mesh,
    params0,
    local_loss: Callable,
    batch_specs: Sequence,
    lr: float = 0.1,
):
    """Build the jitted step.

    - ``params0``: initial params pytree (defines the flat layout).
    - ``local_loss(params, *batch_local) -> scalar``: per-shard loss; runs
      inside shard_map, so it may use ``lax.axis_index``/collectives for
      sp/tp/ep.  Cross-shard loss scaling is handled here (psum / n_dev).
    - ``batch_specs``: one PartitionSpec per batch argument.

    Returns ``(step, flat_store, batch_shardings, store_sharding,
    unravel)`` where ``step(flat_store, *batch) -> (flat_store, loss)``
    donates the store.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.flatten_util import ravel_pytree
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import shard_map_compat

    axes = tuple(mesh.axis_names)
    n_dev = int(np.prod([mesh.shape[a] for a in axes]))

    flat0, unravel = ravel_pytree(params0)
    n_params = flat0.shape[0]
    padded = -(-n_params // n_dev) * n_dev
    flat0 = jnp.pad(flat0, (0, padded - n_params))
    store_sharding = NamedSharding(mesh, P(axes))
    flat_store = jax.device_put(flat0, store_sharding)
    batch_shardings = [NamedSharding(mesh, spec) for spec in batch_specs]

    def _local(store_l, *batch_l):
        flat = lax.all_gather(store_l, axes, tiled=True)[:n_params]
        params = unravel(flat)
        loss, grads = jax.value_and_grad(
            lambda p: local_loss(p, *batch_l)
        )(params)
        flat_g, _ = ravel_pytree(grads)
        flat_g = jnp.pad(flat_g, (0, padded - n_params))
        agg = lax.psum_scatter(flat_g, axes, scatter_dimension=0, tiled=True)
        new_store = store_l - lr * (agg / n_dev)
        return new_store, lax.psum(loss, axes) / n_dev

    fn = shard_map_compat(
        _local, mesh,
        in_specs=(P(axes), *batch_specs),
        out_specs=(P(axes), P()),
    )
    step = jax.jit(fn, donate_argnums=(0,))
    return step, flat_store, batch_shardings, store_sharding, unravel
