"""Local multi-process launcher.

Equivalent of the reference's ``tracker/dmlc_local.py``: spawns 1 scheduler
+ S servers + W workers as OS processes wired by DMLC_* env vars, with the
``keepalive`` elastic-restart loop — a process exiting with code 254 is
re-execed (dmlc_local.py:16-25), which together with scheduler-side
recovery (van.cc:266-332) gives restart-based fault tolerance.

Usage::

    python -m pslite_tpu.tracker.local -n 2 -s 2 [--van tcp] -- \
        python my_app.py args...
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import Dict, List

RESTART_EXIT_CODE = 254


def build_env(
    role: str,
    num_workers: int,
    num_servers: int,
    root_uri: str,
    root_port: int,
    van: str = "tcp",
    group_size: int = 1,
    extra: Dict[str, str] | None = None,
) -> Dict[str, str]:
    env = dict(os.environ)
    env.update(
        DMLC_ROLE=role,
        DMLC_NUM_WORKER=str(num_workers),
        DMLC_NUM_SERVER=str(num_servers),
        DMLC_PS_ROOT_URI=root_uri,
        DMLC_PS_ROOT_PORT=str(root_port),
        DMLC_GROUP_SIZE=str(group_size),
        PS_VAN_TYPE=van,
    )
    if extra:
        env.update(extra)
    return env


class LocalLauncher:
    def __init__(self, num_workers: int, num_servers: int, cmd: List[str],
                 van: str = "tcp", root_port: int = 0, group_size: int = 1,
                 keepalive: bool = True, joint: bool = False):
        if joint and num_workers != num_servers:
            raise ValueError(
                "joint mode hosts one worker+server pair per process; "
                f"num_workers ({num_workers}) must equal num_servers "
                f"({num_servers})"
            )
        self.joint = joint
        from ..utils.network import get_available_port

        self.num_workers = num_workers
        self.num_servers = num_servers
        self.cmd = cmd
        self.van = van
        self.group_size = group_size
        self.keepalive = keepalive
        self.root_port = root_port or get_available_port()
        self.root_uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self._procs: List[tuple] = []  # (role, Popen)
        # PS_CPU_PIN=N: give each spawned node its own disjoint block of
        # N CPUs (sched_setaffinity in the child, Linux only).  Bench
        # harnesses use it for run-to-run reproducibility: free-floating
        # nodes land on scheduler-chosen cores, and a bad draw (worker
        # IO threads sharing cores with the server's pump) shows up as a
        # sticky whole-run throughput mode rather than noise.
        try:
            self._pin_cpus = int(os.environ.get("PS_CPU_PIN", "0") or 0)
        except ValueError:
            self._pin_cpus = 0
        self._pin_next = 0

    def _spawn(self, role: str) -> None:
        env = build_env(
            role, self.num_workers, self.num_servers, self.root_uri,
            self.root_port, self.van, self.group_size,
        )
        env.setdefault("DMLC_NODE_HOST", self.root_uri)
        preexec = None
        if self._pin_cpus > 0 and hasattr(os, "sched_setaffinity"):
            avail = sorted(os.sched_getaffinity(0))
            if self._pin_next + self._pin_cpus > len(avail):
                # Wrapping silently would hand this node cores already
                # pinned to an earlier node — deterministically
                # re-creating the shared-core interference mode the
                # knob exists to eliminate.  Warn so an over-subscribed
                # run is never mistaken for a disjoint one.
                print(
                    f"[tracker] W PS_CPU_PIN={self._pin_cpus}: node "
                    f"#{self._pin_next // self._pin_cpus} wraps past "
                    f"{len(avail)} available CPUs — pinned blocks now "
                    f"OVERLAP earlier nodes",
                    file=sys.stderr, flush=True,
                )
            cpus = frozenset(
                avail[(self._pin_next + j) % len(avail)]
                for j in range(min(self._pin_cpus, len(avail)))
            )
            self._pin_next += self._pin_cpus

            def preexec(cpus=cpus):
                os.sched_setaffinity(0, cpus)
        proc = subprocess.Popen(self.cmd, env=env, preexec_fn=preexec)
        self._procs.append((role, proc))

    def run(self) -> int:
        if self.joint:
            # JOINT deployment (reference ps.h:59-76): each process hosts a
            # worker AND a server; requires num_workers == num_servers.
            roles = ["scheduler"] + ["joint"] * self.num_workers
        else:
            roles = (
                ["scheduler"]
                + ["server"] * self.num_servers
                + ["worker"] * self.num_workers
            )
        for role in roles:
            self._spawn(role)
        # Supervise: restart on RESTART_EXIT_CODE (keepalive), propagate the
        # first real failure, succeed when all workers finish.
        rc = 0
        while self._procs:
            time.sleep(0.2)
            for i, (role, proc) in enumerate(list(self._procs)):
                code = proc.poll()
                if code is None:
                    continue
                self._procs.pop(i)
                if code == RESTART_EXIT_CODE and self.keepalive:
                    print(f"[tracker] restarting {role} (exit 254)",
                          file=sys.stderr)
                    self._spawn(role)
                elif code != 0:
                    rc = code
                    self.terminate()
                break
        return rc

    def terminate(self) -> None:
        for _, proc in self._procs:
            if proc.poll() is None:
                proc.terminate()
        for _, proc in self._procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        self._procs.clear()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, required=True)
    ap.add_argument("--van", default="tcp")
    ap.add_argument("--group-size", type=int, default=1)
    ap.add_argument("--root-port", type=int, default=0)
    ap.add_argument("--joint", action="store_true",
                    help="one process per rank hosting worker+server")
    ap.add_argument("--no-keepalive", action="store_true")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="program to launch (prefix with --)")
    args = ap.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        ap.error("no command given")
    try:
        launcher = LocalLauncher(
            args.num_workers, args.num_servers, cmd, van=args.van,
            root_port=args.root_port, group_size=args.group_size,
            keepalive=not args.no_keepalive, joint=args.joint,
        )
    except ValueError as exc:
        ap.error(str(exc))
    try:
        return launcher.run()
    except KeyboardInterrupt:
        launcher.terminate()
        return 130


if __name__ == "__main__":
    sys.exit(main())
