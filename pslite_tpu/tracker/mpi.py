"""MPI launcher.

Equivalent of the reference's ``tracker/dmlc_mpi.py``: delegates process
placement to ``mpirun`` and derives the PS role from the MPI rank — rank 0
is the scheduler, the next S ranks are servers, the rest workers.  Two
modes:

- driver: ``python -m pslite_tpu.tracker.mpi -n 2 -s 2 -- python app.py``
  execs ``mpirun -np 1+n+s python -m pslite_tpu.tracker.mpi --worker ...``
- per-rank shim (``--worker``): reads ``OMPI_COMM_WORLD_RANK`` /
  ``PMI_RANK``, exports the DMLC_* env, and execs the app.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys

from .local import build_env


def _mpi_rank() -> int:
    for var in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "PMIX_RANK",
                "SLURM_PROCID"):
        val = os.environ.get(var)
        if val is not None:
            return int(val)
    raise RuntimeError("not running under a recognized MPI launcher")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, required=True)
    ap.add_argument("--root-uri", default="127.0.0.1")
    ap.add_argument("--root-port", type=int, default=9091)
    ap.add_argument("--van", default="tcp")
    ap.add_argument("--mpirun", default="mpirun")
    ap.add_argument("--worker", action="store_true",
                    help="internal: per-rank shim under mpirun")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        ap.error("no command given")

    if args.worker:
        rank = _mpi_rank()
        if rank == 0:
            role = "scheduler"
        elif rank <= args.num_servers:
            role = "server"
        else:
            role = "worker"
        env = build_env(role, args.num_workers, args.num_servers,
                        args.root_uri, args.root_port, args.van)
        os.execvpe(cmd[0], cmd, env)

    if shutil.which(args.mpirun) is None:
        print(f"error: {args.mpirun} not found", file=sys.stderr)
        return 127
    np_total = 1 + args.num_workers + args.num_servers
    inner = [
        args.mpirun, "-np", str(np_total),
        sys.executable, "-m", "pslite_tpu.tracker.mpi",
        "-n", str(args.num_workers), "-s", str(args.num_servers),
        "--root-uri", args.root_uri, "--root-port", str(args.root_port),
        "--van", args.van, "--worker", "--",
    ] + cmd
    return subprocess.call(inner)


if __name__ == "__main__":
    sys.exit(main())
