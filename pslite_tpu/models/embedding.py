"""Sparse embedding workload (BASELINE config 5): 1M keys, skewed access.

Zipf-distributed row access over a sharded embedding table, replayed as
sparse push (scatter-add aggregation) + pull through the SparseEngine.
"""

from __future__ import annotations

import numpy as np


def skewed_indices(num_rows: int, workers: int, batch: int, seed: int = 0,
                   a: float = 1.2) -> np.ndarray:
    """[workers, batch] Zipf(a)-skewed row ids (hot-key heavy)."""
    rng = np.random.default_rng(seed)
    idx = rng.zipf(a, size=(workers, batch)).astype(np.int64)
    return ((idx - 1) % num_rows).astype(np.int32)


def replay(sparse_engine, num_rows: int = 1 << 20, dim: int = 64,
           batch: int = 4096, steps: int = 1, seed: int = 0,
           measure=None):
    """Returns (bytes_moved_per_step, seconds_per_step).  ``measure``
    swaps the clock (see resnet_trace.replay); with it, dt may be None
    when the requested basis is unavailable."""
    import time

    name = f"emb_{num_rows}_{dim}"
    if name not in sparse_engine._tables:
        sparse_engine.register_sparse(name, num_rows, dim)
    W = sparse_engine.num_shards
    idx = skewed_indices(num_rows, W, batch, seed=seed)
    grads = np.ones((W, batch, dim), dtype=np.float32)

    sparse_engine.push(name, idx, grads)
    out = sparse_engine.pull(name, idx)
    out.block_until_ready()  # warm the executable cache

    def loop():
        for _ in range(steps):
            sparse_engine.push(name, idx, grads)
            out = sparse_engine.pull(name, idx)
        out.block_until_ready()
        sparse_engine.block(name)

    from ..utils.profiling import clocked

    elapsed = clocked(loop, measure)
    dt = elapsed / max(steps, 1) if elapsed is not None else None
    step_bytes = 2 * 4 * W * batch * dim  # push + pull payload
    return step_bytes, dt
