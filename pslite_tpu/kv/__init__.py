from .kv_app import (KVMeta, KVPairs, KVServer, KVServerDefaultHandle,
                     KVServerOptimizerHandle, KVWorker)
from .simple_app import SimpleApp, SimpleData

__all__ = [
    "KVMeta",
    "KVPairs",
    "KVServer",
    "KVServerDefaultHandle",
    "KVServerOptimizerHandle",
    "KVWorker",
    "SimpleApp",
    "SimpleData",
]
