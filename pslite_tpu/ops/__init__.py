"""Pallas TPU kernels for the PS hot paths.

The reference's hot loops are NIC-side (RDMA write batching); on TPU the
equivalents are HBM-side: fused optimizer application on server shards
(one HBM pass instead of several) and blockwise int8 quantization for
bandwidth-compressed push/pull over DCN-class links.
"""

from .fused_update import adam_update, sgd_update
from .quantize import dequantize_int8, quantize_int8

__all__ = ["adam_update", "sgd_update", "quantize_int8", "dequantize_int8"]
