"""Resender — optional ACK/dedup/retransmit reliability layer.

Capability parity with the reference's ``src/resender.h``: every sent message
is buffered under a signature; the receiver acks everything and drops
duplicates; a monitor thread retransmits entries older than
``PS_RESEND_TIMEOUT`` ms, up to 10 retries.  Enabled with ``PS_RESEND=1``;
exercised together with the ``PS_DROP_MSG`` fault injector.

Retransmits go through ``van.send_msg_locked``, which routes each data
message into its destination peer's SEND LANE (van.py): the monitor
thread only enqueues, so one dead peer blocking on its socket cannot
head-of-line-block retransmits to healthy peers — and the retransmit
cannot interleave mid-frame with that lane's in-flight send, because
the lane's transmit lock serializes the actual wire writes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Tuple

from ..message import Command, Control, Message
from ..utils import logging as log
from ..utils.bounded import BoundedKeySet


def _signature(msg: Message) -> int:
    m = msg.meta
    # Unlike the reference (which truncates ids to 8 bits — resender.h:98-100,
    # a known quirk), hash the full ids so large clusters stay collision-free.
    # option+addr are part of the identity: replication forwards carry the
    # ORIGIN (worker, timestamp) of the push they relay, so two forwards
    # relaying different workers' pushes share every other field — without
    # addr in the hash the receiver would drop the second as a duplicate.
    # sid too: it distinguishes a deadline-sweeper RETRY (a new message,
    # fresh sid at dispatch) from a van-level retransmit of the original
    # (same message, sid kept) — retransmit dupes still dedup, while a
    # retry whose original REQUEST was delivered but whose RESPONSE was
    # lost reaches the app again instead of being silently ack-dropped.
    # All three fields are stable across retransmits of one message.
    # Chunked transfers (docs/chunking.md) lean on sid the same way:
    # the N chunks of one transfer share every app-level field and
    # differ only in sid, so each chunk is tracked, acked, and
    # retransmitted INDEPENDENTLY — a drop costs one chunk's resend,
    # not the whole transfer.
    return hash(
        (m.app_id, m.customer_id, m.sender, m.recver, m.timestamp, m.request,
         m.push, m.simple_app, m.key, m.option, m.addr, m.sid, m.control.cmd)
    ) & ((1 << 64) - 1)


class Resender:
    def __init__(self, van, timeout_ms: int, max_retries: int = 10):
        self._van = van
        self._timeout_s = timeout_ms / 1000.0
        self._max_retries = max_retries
        self._mu = threading.Lock()
        self._send_buff: Dict[int, Tuple[Message, float, int]] = {}
        # Telemetry (docs/observability.md): retransmit volume is THE
        # health signal of a lossy link, and ack-cache evictions bound
        # how long the dedup window actually is in practice.  Test
        # doubles without a registry degrade to the no-op singletons.
        from ..telemetry.metrics import NULL_REGISTRY

        metrics = getattr(van, "metrics", None) or NULL_REGISTRY
        self._c_retransmits = metrics.counter("resender.retransmits")
        self._c_giveups = metrics.counter("resender.giveups")
        self._c_dup_dropped = metrics.counter("resender.dup_dropped")
        evict = metrics.counter("resender.ack_cache_evictions")
        # Receive-side dedup signatures, bounded FIFO: the reference's
        # (and our former) unbounded set leaks ~8 bytes per message
        # forever on long runs.  ~64k signatures cover far more in-
        # flight traffic than any retransmit window can hold; a sig
        # evicted this long after its ack can only dedup a duplicate
        # that 10 retransmit timeouts have already passed by.
        self._acked = BoundedKeySet(
            max(1024, van.env.find_int("PS_RESEND_ACK_CACHE", 65536)),
            on_evict=lambda _sig: evict.inc(),
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._monitoring, name="resender", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def drain(self, max_wait_s: float = 5.0) -> bool:
        """Keep retransmitting until every buffered message is acked (or the
        deadline passes).  Called before shutdown so peers whose barrier
        replies were dropped still get them; without this a lossy link can
        strand a peer in finalize forever."""
        deadline = time.monotonic() + max_wait_s
        while time.monotonic() < deadline:
            with self._mu:
                if not self._send_buff:
                    return True
            time.sleep(self._timeout_s / 4)
        with self._mu:
            return not self._send_buff

    def add_outgoing(self, msg: Message) -> None:
        if msg.meta.control.cmd in (Command.ACK, Command.TERMINATE):
            return
        sig = _signature(msg)
        msg.meta.control.msg_sig = sig
        with self._mu:
            self._send_buff[sig] = (msg, time.monotonic(), 0)

    def add_incoming(self, msg: Message) -> bool:
        """Returns True if the message was consumed (ACK) or is a duplicate."""
        cmd = msg.meta.control.cmd
        if cmd == Command.TERMINATE:
            return False
        if cmd == Command.ACK:
            with self._mu:
                self._send_buff.pop(msg.meta.control.msg_sig, None)
            return True
        sig = msg.meta.control.msg_sig or _signature(msg)
        if msg.meta.sender >= 0:
            ack = Message()
            ack.meta.recver = msg.meta.sender
            ack.meta.control = Control(cmd=Command.ACK, msg_sig=sig)
            try:
                # Runs on the receive pump: a transport error (sender
                # died between its send and our ack) must not kill it —
                # the sender's retransmit path owns that failure.
                self._van.send(ack)
            except Exception as exc:  # noqa: BLE001
                log.vlog(1, f"ack to {msg.meta.sender} failed: {exc!r}")
        with self._mu:
            duplicated = not self._acked.add(sig)
        if duplicated:
            self._c_dup_dropped.inc()
            log.vlog(2, lambda: f"Duplicated message dropped: {msg.debug_string()}")
        return duplicated

    def forget(self, sig: int) -> None:
        """Stop tracking one outgoing message (the owning request was
        failed over to another destination; retransmitting the original
        would only end in a spurious give-up)."""
        with self._mu:
            self._send_buff.pop(sig, None)

    def _monitoring(self) -> None:
        while not self._stop.wait(self._timeout_s / 2):
            now = time.monotonic()
            resend = []
            gave_up = []
            with self._mu:
                for sig, (msg, sent_at, retries) in list(self._send_buff.items()):
                    if self._van.is_peer_down(msg.meta.recver):
                        # The failure detector already declared the
                        # destination dead: burning the remaining retry
                        # budget against it only delays the owner's
                        # failover.
                        del self._send_buff[sig]
                        gave_up.append((msg, retries, "peer declared dead"))
                        continue
                    if now - sent_at <= self._timeout_s:
                        continue
                    if retries >= self._max_retries:
                        del self._send_buff[sig]
                        gave_up.append(
                            (msg, retries, f"{retries} retries exhausted")
                        )
                        continue
                    self._send_buff[sig] = (msg, now, retries + 1)
                    resend.append(msg)
            for msg, retries, why in gave_up:
                self._c_giveups.inc()
                log.warning(
                    f"Failed to deliver ({why}): {msg.debug_string()}"
                )
                # Flight recorder (docs/observability.md): a give-up is
                # the terminal fault of the reliability layer — the
                # postmortem wants the peer, the retry count, and why.
                flight = getattr(self._van, "flight", None)
                if flight is not None:
                    flight.record(
                        "retransmit_giveup", severity="warn",
                        peer=msg.meta.recver, retries=retries, why=why,
                        ts=msg.meta.timestamp,
                    )
                # Fail the owning request (or park a van error) instead
                # of the old silent delete, which left the waiting
                # caller hanging forever on a message the resender had
                # already abandoned.
                try:
                    self._van._delivery_failed(
                        msg, ConnectionError(f"resender gave up: {why}")
                    )
                except Exception as exc:  # noqa: BLE001
                    log.warning(f"delivery-failure report failed: {exc!r}")
            for msg in resend:
                self._c_retransmits.inc()
                log.vlog(1, f"Resend {msg.debug_string()}")
                try:
                    # Routed through the owning peer's send lane (no sid
                    # re-assignment, no re-buffering); lane-side failures
                    # surface via the van's parked-error path, not here.
                    self._van.send_msg_locked(msg)
                except Exception as exc:
                    log.warning(f"resend failed: {exc!r}")
