"""Headline benchmark: dense KV push-pull application goodput.

Mirrors the reference's ``tests/test_benchmark`` PUSH_PULL mode
(test_benchmark.cc:388-396): goodput counts application payload bytes
(push + pull) per wall-clock second, over the default dense workload
(40 keys x 1 MB, repeat-timed).  Runs on whatever accelerator JAX exposes
(the real TPU chip under the driver; do NOT set JAX_PLATFORMS=cpu here).

``vs_baseline``: the reference publishes no absolute numbers
(BASELINE.json "published": {}); the driver-defined pass bar is >= 70% of
ICI line rate.  We normalize against 0.7 x 100 GB/s = 70 GB/s per chip —
a v5e-class per-chip ICI budget — so vs_baseline >= 1.0 means the bar is
met on the measured path.

Prints ONE JSON line.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pslite_tpu.parallel.engine import CollectiveEngine

    eng = CollectiveEngine()
    num_keys = 40  # NUM_KEY_PER_SERVER default (test_benchmark.cc:407-414)
    val_len = (1 << 20) // 4  # 1 MB per key, fp32
    keys = np.arange(num_keys, dtype=np.uint64)
    eng.register_dense("bench", keys, val_len)
    bucket = eng.bucket("bench")

    sharding = NamedSharding(eng.mesh, P(eng.axis, None))
    grads = jax.device_put(
        jnp.ones((eng.num_shards, bucket.padded_len), jnp.float32), sharding
    )

    # Warmup: compile + first-touch (the rendezvous equivalent).
    for _ in range(3):
        out = eng.push_pull("bench", grads)
    out.block_until_ready()

    iters = 30
    t0 = time.perf_counter()
    for _ in range(iters):
        out = eng.push_pull("bench", grads)
    out.block_until_ready()
    elapsed = time.perf_counter() - t0

    payload = num_keys * val_len * 4  # bytes per direction
    total_bytes = 2 * payload * iters  # push + pull
    goodput_gbps = total_bytes / elapsed / 1e9
    baseline = 70.0  # GB/s: 70% of a ~100 GB/s per-chip ICI budget
    print(
        json.dumps(
            {
                "metric": "dense push-pull goodput (40x1MB, fused RS+update+AG)",
                "value": round(goodput_gbps, 2),
                "unit": "GB/s/chip",
                "vs_baseline": round(goodput_gbps / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
