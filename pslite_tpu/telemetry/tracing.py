"""Distributed request tracing — Chrome trace-event JSON per node.

A trace id is minted at ``KVWorker.push/pull`` with probability
``PS_TRACE_SAMPLE`` and rides in ``Message.meta.trace`` (a
backward-compatible wire extension — see ``wire.py``), so every process
that touches the request can record lifecycle spans against the same
id: enqueue → lane-dequeue → wire-send on the worker, recv → apply →
respond on the server, completion back on the worker.

Each node buffers its spans locally (bounded — sampling plus the cap
make this safe under full load) and exports ONE Chrome trace-event JSON
file on shutdown (or on demand).  Timestamps are ``monotonic_ns``
offsets re-based onto a single wall-clock anchor captured at tracer
construction, so per-node files from one cluster merge on a shared
timeline in Perfetto (open them together, or concatenate the
``traceEvents`` arrays — docs/observability.md).
"""

from __future__ import annotations

import json
import os
import random
import tempfile
import threading
from typing import List, Optional

from ..utils.profiling import MonotonicAnchor


class Tracer:
    """Per-node span recorder.  ``active`` is False unless
    ``PS_TRACE_SAMPLE > 0`` — every recording call no-ops then, so the
    tracer costs one attribute check on untraced deployments."""

    MAX_EVENTS = 65536

    def __init__(self, env, role: str, metrics=None):
        self.sample = env.find_float("PS_TRACE_SAMPLE", 0.0)
        self.active = self.sample > 0.0
        self.role = role
        self.node_id = -1  # assigned at bootstrap (export-time pid)
        # Default export into the system tempdir, NOT the cwd: traced
        # clusters launched from a checkout were littering (and once
        # committing) pslite_trace_*.json at the repo root.  The files
        # are also gitignored; set PS_TRACE_DIR to collect them.
        self._dir = env.find("PS_TRACE_DIR") or tempfile.gettempdir()
        self._mu = threading.Lock()
        self._events: List[dict] = []
        self.dropped = 0
        # Silent span loss made visible (docs/observability.md): every
        # buffer-full drop also counts on the node registry, so the
        # METRICS_PULL snapshot carries ``trace.dropped_events`` and
        # psmon can warn that the exported trace is INCOMPLETE.  The
        # legacy ``dropped`` attribute remains the local read view.
        if metrics is not None:
            self._c_dropped = metrics.counter("trace.dropped_events")
        else:
            from .metrics import NULL_REGISTRY

            self._c_dropped = NULL_REGISTRY.counter("trace.dropped_events")
        # Cross-node clock alignment: durations come from monotonic_ns,
        # absolute timestamps re-base onto ONE wall anchor per tracer
        # (the Profiler's timebase — utils/profiling.MonotonicAnchor).
        self._anchor = MonotonicAnchor()

    # -- ids & clock ---------------------------------------------------------

    def maybe_trace(self) -> int:
        """A fresh nonzero trace id when this request is sampled, else
        0 (untraced — every downstream stage checks the id, not the
        sampling knob, so the decision is made exactly once)."""
        if not self.active or random.random() >= self.sample:
            return 0
        return random.getrandbits(63) | 1

    def now_us(self) -> float:
        """Wall-aligned monotonic microseconds (the event timebase)."""
        return self._anchor.now_ns() / 1000.0

    # -- recording -----------------------------------------------------------

    def _append(self, ev: dict) -> None:
        with self._mu:
            if len(self._events) >= self.MAX_EVENTS:
                self.dropped += 1
                self._c_dropped.inc()
                return
            self._events.append(ev)

    def span(self, trace_id: int, name: str, t0_us: float,
             dur_us: Optional[float] = None, args: Optional[dict] = None)\
            -> None:
        """A complete ("X") span: ``[t0_us, t0_us + dur_us]``.  With
        ``dur_us`` omitted, the span ends now."""
        if not trace_id or not self.active:
            return
        if dur_us is None:
            dur_us = max(0.0, self.now_us() - t0_us)
        a = {"trace": f"{trace_id:x}"}
        if args:
            a.update(args)
        self._append({
            "name": name, "cat": "pslite", "ph": "X",
            "ts": t0_us, "dur": dur_us,
            "tid": threading.get_ident() & 0xFFFF,
            "args": a,
        })

    def instant(self, trace_id: int, name: str,
                args: Optional[dict] = None) -> None:
        if not trace_id or not self.active:
            return
        a = {"trace": f"{trace_id:x}"}
        if args:
            a.update(args)
        self._append({
            "name": name, "cat": "pslite", "ph": "i",
            "ts": self.now_us(), "s": "t",
            "tid": threading.get_ident() & 0xFFFF,
            "args": a,
        })

    # -- export --------------------------------------------------------------

    @property
    def num_events(self) -> int:
        with self._mu:
            return len(self._events)

    def default_path(self) -> str:
        return os.path.join(
            self._dir, f"pslite_trace_{self.role}_{self.node_id}.json"
        )

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the buffered spans as Chrome trace-event JSON; returns
        the path, or None when nothing was recorded.  Idempotent: the
        buffer is kept, a later export rewrites the same file with any
        additional spans."""
        with self._mu:
            events = list(self._events)
        if not events:
            return None
        pid = self.node_id
        label = f"{self.role} {pid}"
        out = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": label},
        }]
        for ev in events:
            ev = dict(ev)
            ev["pid"] = pid
            out.append(ev)
        path = path or self.default_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, fh)
        os.replace(tmp, path)
        return path

    def export_if_any(self) -> Optional[str]:
        if not self.active or self.num_events == 0:
            return None
        return self.export()


class _NullTracer:
    """Do-nothing tracer for stub postoffices (benches)."""

    active = False
    sample = 0.0
    node_id = -1
    num_events = 0

    def maybe_trace(self) -> int:
        return 0

    def now_us(self) -> float:
        return 0.0

    def span(self, *a, **kw) -> None:
        pass

    def instant(self, *a, **kw) -> None:
        pass

    def export(self, path=None):
        return None

    def export_if_any(self):
        return None


NULL_TRACER = _NullTracer()
