"""Cross-slice (DCN) tier: hierarchical dense push/pull.

A TPU pod slice talks ICI internally; slices talk to each other over DCN.
The reference's analogous structures are BytePS's hierarchical reduction
and the MultiVan rail composition (multi_van.h:173-197: route each
message across N inner vans).  Here the two tiers compose the two
existing data planes:

1. **ICI tier** — intra-slice aggregation: one fused
   ``psum_scatter + all_gather`` (an all-reduce) on the slice's
   :class:`CollectiveEngine`, producing the slice-local gradient sum.
2. **DCN tier** — inter-slice exchange: each slice's leader pushes the
   slice-sum through the ordinary KV message path (:class:`KVWorker`
   over a socket van).  The default slicer shards the keys across the
   global servers, so DCN traffic is key-range partitioned across
   server rails exactly like MultiVan routes across its inner vans; the
   server handler applies the update (sum / optimizer — the same
   pluggable handle contract, kv_app.h:430-452).
3. **Redistribute** — the pulled global aggregate is placed replicated
   onto the slice mesh for consumption by the slice's devices.

The leader barriers on the worker group between push and pull so every
slice's contribution lands before any slice reads the aggregate (the
synchronous-SGD pattern of the reference's docs/overview.md).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..base import WORKER_GROUP
from ..utils import logging as log


class DcnKVWorker:
    """Hierarchical dense push/pull: slice mesh (ICI) + KV messages (DCN).

    ``kv_worker`` is this slice leader's :class:`KVWorker` on a socket
    van connecting the slices; ``slice_engine`` is the slice's
    :class:`CollectiveEngine`.  One instance per slice leader process.
    """

    def __init__(self, kv_worker, slice_engine, barrier=True):
        self.kv = kv_worker
        self.engine = slice_engine
        self._barrier = barrier
        self._keys: dict = {}

    def register_dense(self, name: str, keys, val_len: int,
                       dtype=None) -> None:
        """Register the bucket on both tiers (engine scratch + KV keys)."""
        keys = np.ascontiguousarray(np.asarray(keys, dtype=np.uint64))
        self.engine.register_dense(name, keys, val_len, dtype=dtype)
        self._keys[name] = keys

    def push_pull(self, name: str, grads, out: Optional[np.ndarray] = None):
        """grads: this slice's worker rows ([W_slice, total] or [total]).

        Returns the global (all-slice) aggregate as a host array, also
        written to ``out`` when given.  Synchronous across slices.
        """
        log.check(name in self._keys, f"bucket {name!r} not registered")
        bucket = self.engine.bucket(name)
        # ICI tier: slice-local all-reduce.  handle="assign" makes the
        # engine store pure scratch (store := slice sum), so the global
        # accumulation semantics live only at the DCN servers.
        slice_sum = np.asarray(
            self.engine.push_pull(name, grads, handle="assign")
        )
        # DCN tier: key-range-sharded push to the global servers, then a
        # barrier so every slice's push is applied before any pull.
        keys = self._keys[name]
        ts = self.kv.push(keys, slice_sum)
        self.kv.wait(ts)
        if self._barrier:
            self.kv.po.barrier(self.kv._customer.customer_id, WORKER_GROUP)
        if out is None:
            out = np.empty(bucket.total_len, dtype=np.dtype(bucket.dtype))
        self.kv.wait(self.kv.pull(keys, out))
        if self._barrier:
            # Post-pull barrier: without it a fast slice's NEXT-round push
            # could land at the sum-accumulating servers before a slow
            # slice finishes reading THIS round's aggregate.
            self.kv.po.barrier(self.kv._customer.customer_id, WORKER_GROUP)
        return out

    def to_device(self, name: str, host_aggregate):
        """Place the pulled aggregate replicated onto the slice mesh (the
        intra-slice redistribution step)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.engine.mesh, P(None))
        return jax.device_put(np.asarray(host_aggregate), sharding)
