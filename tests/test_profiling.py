"""Per-message event tracing (ENABLE_PROFILING), van byte counters."""

import numpy as np

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker

from helpers import LoopbackCluster


def test_profiler_event_log_and_byte_counters(tmp_path):
    path = tmp_path / "trace.csv"
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={"ENABLE_PROFILING": "1", "PROFILE_PATH": str(path)},
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([9], dtype=np.uint64)
        vals = np.ones(32, dtype=np.float32)
        worker.wait(worker.push(keys, vals))
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))

        van = cluster.workers[0].van
        assert van.send_bytes > 0
        assert van.recv_bytes > 0
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()

    lines = path.read_text().strip().splitlines()
    # key,event_kind,timestamp_us — the reference's (key, event, µs) format.
    assert any(line.startswith("9,send_push,") for line in lines), lines
    assert any(line.startswith("9,recv_pull,") for line in lines), lines
    for line in lines:
        key, event, ts = line.split(",")
        assert event.split("_")[0] in ("send", "recv")
        assert int(ts) > 0


def test_engine_path_events_and_byte_counters(tmp_path):
    """ENABLE_PROFILING must cover the collective fast path too
    (engine-path analog of van.cc:29-77): per-op
    (bucket, op, ts, bytes, µs) events plus engine byte counters next to
    Van.send_bytes/recv_bytes."""
    import pytest

    pytest.importorskip("jax")
    path = tmp_path / "engine_trace.csv"
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="ici",
        env_extra={"ENABLE_PROFILING": "1", "PROFILE_PATH": str(path)},
    )
    cluster.start()
    try:
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.arange(2, dtype=np.uint64)
        val_len = 16
        worker.register_dense("prof", keys, val_len)
        vals = np.ones(2 * val_len, dtype=np.float32)
        outs = np.zeros_like(vals)
        worker.wait(worker.push_pull(keys, vals, outs))
        worker.wait(worker.push(keys, vals))
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))

        eng = worker.engine
        payload = 2 * val_len * 4
        assert eng.push_bytes == 2 * payload  # push_pull + push
        assert eng.pull_bytes == 2 * payload  # push_pull + pull
    finally:
        cluster.finalize()

    lines = path.read_text().strip().splitlines()
    engine_lines = [ln for ln in lines if "_engine," in ln]
    ops = {ln.split(",")[1] for ln in engine_lines}
    assert "push_pull_engine" in ops, lines
    assert "push_engine" in ops, lines
    assert "pull_engine" in ops, lines
    for ln in engine_lines:
        bucket, op, ts, nbytes, dur = ln.split(",")
        assert bucket == "prof"
        assert int(ts) > 0 and int(nbytes) > 0 and int(dur) >= 0
