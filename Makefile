# Top-level build/test entry points (reference: Makefile + make/ps.mk).
#
#   make native         build the C++ transport core
#   make native ASAN=1  ... with AddressSanitizer
#   make native TSAN=1  ... with ThreadSanitizer (io thread vs callers)
#   make test           run the full suite (virtual 8-device CPU mesh)
#   make bench          run the headline benchmark on the local accelerator
#   make lint           byte-compile every Python module

ASAN ?= 0
TSAN ?= 0
ifeq ($(ASAN)$(TSAN), 11)
$(error ASAN and TSAN are mutually exclusive)
endif
ifeq ($(ASAN), 1)
CPPFLAGS_EXTRA = CXXFLAGS="-O1 -g -std=c++17 -fPIC -Wall -Wextra -pthread -fsanitize=address"
endif
ifeq ($(TSAN), 1)
CPPFLAGS_EXTRA = CXXFLAGS="-O1 -g -std=c++17 -fPIC -Wall -Wextra -pthread -fsanitize=thread"
endif

.PHONY: all native test bench lint clean

all: native

native:
	$(MAKE) -C cpp $(CPPFLAGS_EXTRA)

test: native
	python -m pytest tests/ -x -q

bench: native
	python bench.py

lint:
	python -m compileall -q pslite_tpu tests bench.py __graft_entry__.py

clean:
	$(MAKE) -C cpp clean
	find . -name __pycache__ -type d -exec rm -rf {} +
