"""Replica read fan-out + versioned model namespaces
(docs/serving_reads.md): pull spread across the whole replica chain
with push-stamp read-your-writes, stale-replica fallback, chaos
kill-a-replica, live namespace flip/rollback under storm, hot-cache
stamp interplay, and join-time replica backfill.
"""

import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker  # noqa: E402
from pslite_tpu.base import server_rank_to_id  # noqa: E402
from pslite_tpu.kv.replication import chain_ranks  # noqa: E402

from helpers import LoopbackCluster  # noqa: E402

# Every storm below aims at server rank 0's key range (uniform split
# of the uint64 space over 3 servers), so its whole chain serves.
ROWS = 96
DIM = 8
KEYS = np.arange(ROWS, dtype=np.uint64)

RR_ENV = {
    "PS_KV_REPLICATION": "3",
    "PS_REPLICA_READS": "1",
    # rr exercises every chain member even from a single worker (the
    # sticky default would pin one worker to one member).
    "PS_REPLICA_READ_POLICY": "rr",
    "PS_REQUEST_TIMEOUT": "2.0",
    "PS_REQUEST_RETRIES": "8",
    "PS_HOT_CACHE": "0",
}


def _spin_up(cluster):
    servers = []
    for po in cluster.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    workers = [KVWorker(0, 0, postoffice=po) for po in cluster.workers]
    return servers, workers


def _teardown(cluster, servers, workers, dead_pos=()):
    for w in workers:
        w.stop()
    for s in servers:
        if s.po not in dead_pos:
            s.stop()
    for po in cluster.all_nodes():
        try:
            po.van.stop()
        except Exception:  # noqa: BLE001 - already stopped
            pass


def _table(scale=1.0):
    return np.stack([np.full(DIM, scale * (1.0 + r), np.float32)
                     for r in range(ROWS)])


def _push_table(worker, table):
    worker.wait(worker.push(KEYS, np.ascontiguousarray(table).reshape(-1)))


def _settle(worker, expected, timeout=10.0):
    """Poll until replicas serve the full expected table (forwards are
    async; only after this do bit-exact assertions arm)."""
    out = np.zeros(ROWS * DIM, np.float32)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out[:] = 0
        worker.wait(worker.pull(KEYS, out))
        if np.array_equal(out.reshape(ROWS, DIM), expected):
            return
        time.sleep(0.05)
    raise TimeoutError("replicas never converged on the pushed table")


def test_spread_and_bit_exact():
    """Round-robin replica reads hit EVERY live chain member, and every
    answer is bit-exact with the pushed table (forwards preserve the
    primary's arrival order)."""
    cluster = LoopbackCluster(num_workers=1, num_servers=3,
                              env_extra=RR_ENV)
    cluster.start()
    servers, workers = _spin_up(cluster)
    w = workers[0]
    try:
        table = _table()
        _push_table(w, table)
        _settle(w, table)
        out = np.zeros(16 * DIM, np.float32)
        for i in range(30):
            start = (i * 3) % (ROWS - 16)
            out[:] = 0
            w.wait(w.pull(KEYS[start:start + 16], out))
            np.testing.assert_array_equal(
                out.reshape(16, DIM), table[start:start + 16])
        # The spread reached beyond the primary...
        assert w.po.metrics.counter("replica_read.spread").value > 0
        # ...and every chain member answered pulls.
        assert len(w._read_share) == 3, w._read_share
        assert all(n > 0 for n in w._read_share.values()), w._read_share
    finally:
        _teardown(cluster, servers, workers)


def test_read_your_writes_under_racing_push_storm():
    """Push-then-immediately-pull NEVER returns a value missing the
    worker's own push, even while a background storm keeps the forward
    pipeline saturated and 2/3 of the pulls land on replicas."""
    cluster = LoopbackCluster(num_workers=1, num_servers=3,
                              env_extra=RR_ENV)
    cluster.start()
    servers, workers = _spin_up(cluster)
    w = workers[0]
    try:
        table = _table()
        _push_table(w, table)
        _settle(w, table)
        stop = threading.Event()
        storm_keys = KEYS[:32]
        storm_delta = np.ones(32 * DIM, np.float32)
        storm_pushes = [0]

        def storm():
            # Saturates the primary->replica forward stream so probe
            # pulls race real replication traffic.
            while not stop.is_set():
                w.wait(w.push(storm_keys, storm_delta))
                storm_pushes[0] += 1

        t = threading.Thread(target=storm, daemon=True)
        t.start()
        try:
            probe_keys = KEYS[ROWS - 8:]
            expected = np.ascontiguousarray(table[ROWS - 8:])
            delta = np.ones(8 * DIM, np.float32)
            out = np.zeros(8 * DIM, np.float32)
            for _ in range(40):
                expected += 1.0
                w.wait(w.push(probe_keys, delta))
                out[:] = 0
                w.wait(w.pull(probe_keys, out))
                # Read-your-writes: the answer must include THIS
                # worker's newest acknowledged push, whichever chain
                # member served it.
                np.testing.assert_array_equal(out.reshape(8, DIM),
                                              expected)
        finally:
            stop.set()
            t.join(timeout=10)
        assert storm_pushes[0] > 0
    finally:
        _teardown(cluster, servers, workers)


def test_stale_replica_answer_repulls_from_primary():
    """A replica answer whose applied stamp trails the worker's own
    push frontier is DISCARDED and re-pulled from the primary: forcing
    the frontier far ahead makes every replica answer stale, yet every
    pull still returns correct data (via the primary) and the fallback
    counter + flight event record the discounts."""
    cluster = LoopbackCluster(num_workers=1, num_servers=3,
                              env_extra=RR_ENV)
    cluster.start()
    servers, workers = _spin_up(cluster)
    w = workers[0]
    try:
        table = _table()
        _push_table(w, table)
        _settle(w, table)
        primary_id = server_rank_to_id(0)
        # Pretend we have seen a push far beyond anything the replicas
        # will ever claim: every replica-served answer is now stale.
        with w._mu:
            w._seen_stamps[primary_id] = 1 << 40
        out = np.zeros(8 * DIM, np.float32)
        for _ in range(9):
            out[:] = 0
            w.wait(w.pull(KEYS[:8], out))
            np.testing.assert_array_equal(out.reshape(8, DIM),
                                          table[:8])
        fallbacks = w.po.metrics.counter("replica_read.fallbacks").value
        assert fallbacks > 0
        assert w.po.flight.events("replica_stale_fallback")
    finally:
        _teardown(cluster, servers, workers)


def test_chaos_kill_replica_mid_read_storm():
    """A replica crashing mid read storm never fails a wait: the dead
    member drops out of the spread set (peer-down exclusion) and its
    in-flight pulls retry onto live members."""
    env = dict(RR_ENV)
    env.update({
        "PS_HEARTBEAT_INTERVAL": "0.3",
        "PS_HEARTBEAT_TIMEOUT": "1.0",
        "PS_REQUEST_TIMEOUT": "0.5",
    })
    cluster = LoopbackCluster(
        num_workers=1, num_servers=3, env_extra=env,
        van_type="chaos+loopback",
        per_node_env={"server1": {"PS_CHAOS": "crash=recv:40"}},
    )
    cluster.start()
    servers, workers = _spin_up(cluster)
    w = workers[0]
    dead_po = next(po for po in cluster.servers
                   if po.van.my_node.id == server_rank_to_id(1))
    try:
        table = _table()
        _push_table(w, table)
        _settle(w, table)
        out = np.zeros(16 * DIM, np.float32)
        for i in range(150):
            start = (i * 5) % (ROWS - 16)
            out[:] = 0
            # Every wait must succeed — a crashed replica's pull
            # retries to a live member, never times out the request.
            w.wait(w.pull(KEYS[start:start + 16], out))
            np.testing.assert_array_equal(
                out.reshape(16, DIM), table[start:start + 16])
        assert dead_po.van.chaos_crashed.is_set(), \
            "victim never crashed — scenario inert"
    finally:
        _teardown(cluster, servers, workers, dead_pos=(dead_po,))


def test_namespace_flip_and_rollback_under_pull_storm():
    """A published model version flips in atomically under a live pull
    storm — zero failed requests, every answer bit-exact against
    exactly one version — and rollback restores the displaced store."""
    snapdir = tempfile.mkdtemp(prefix="ps_nsflip_test_")
    env = dict(RR_ENV)
    env["PS_SNAPSHOT_DIR"] = snapdir
    cluster = LoopbackCluster(num_workers=1, num_servers=3,
                              env_extra=env)
    cluster.start()
    servers, workers = _spin_up(cluster)
    w = workers[0]
    try:
        v1 = _table()
        _push_table(w, v1)
        _settle(w, v1)
        cluster.scheduler.snapshot()
        _push_table(w, v1)  # the live (additive) store is now 2*v1
        v2 = 2 * v1
        _settle(w, v2)
        stop = threading.Event()
        errors = []
        pulls = [0]

        def storm():
            out = np.zeros(16 * DIM, np.float32)
            i = 0
            while not stop.is_set():
                start = (i * 7) % (ROWS - 16)
                i += 1
                out[:] = 0
                try:
                    w.wait(w.pull(KEYS[start:start + 16], out))
                except Exception as exc:  # noqa: BLE001
                    errors.append(repr(exc))
                    continue
                got = out.reshape(16, DIM)
                if not (np.array_equal(got, v1[start:start + 16])
                        or np.array_equal(got, v2[start:start + 16])):
                    errors.append(f"mixed-version read at row {start}")
                pulls[0] += 1

        t = threading.Thread(target=storm, daemon=True)
        t.start()
        try:
            time.sleep(0.2)
            pub = cluster.scheduler.publish_model(namespace="m",
                                                  version="v1")
            assert pub["servers"] == 3, pub
            time.sleep(0.2)
            rb = cluster.scheduler.rollback_model()
            assert rb["servers"] == 3, rb
            time.sleep(0.2)
        finally:
            stop.set()
            t.join(timeout=10)
        assert not errors, errors[:5]
        assert pulls[0] > 0
        # Post-rollback, the LIVE (v2) store serves again.
        out = np.zeros(ROWS * DIM, np.float32)
        w.wait(w.pull(KEYS, out))
        np.testing.assert_array_equal(out.reshape(ROWS, DIM), v2)
        # Every server recorded the flip and the rollback.
        for s in servers:
            assert s.po.flight.events("namespace_flip")
            assert s.po.flight.events("namespace_rollback")
    finally:
        _teardown(cluster, servers, workers)
        shutil.rmtree(snapdir, ignore_errors=True)


def test_hot_cache_fill_from_replica_carries_primary_identity():
    """A cache fill from a replica-served pull is recorded under the
    PRIMARY's node id with the replica's applied stamp (the same
    counter domain): the primary's next push-ack stamp then lazily
    invalidates it — a pull after a push never serves the displaced
    cached value."""
    env = dict(RR_ENV)
    env["PS_HOT_CACHE"] = "1"
    cluster = LoopbackCluster(num_workers=1, num_servers=3,
                              env_extra=env)
    cluster.start()
    servers, workers = _spin_up(cluster)
    w = workers[0]
    try:
        table = _table()
        _push_table(w, table)
        _settle(w, table)
        primary_id = server_rank_to_id(0)
        time.sleep(0.3)  # forwards drain: replicas answer fresh
        # The settle pulls filled the cache (and cache hits never
        # route): flush it so the probe pulls below actually travel.
        w._hot_cache.invalidate_range(0, (1 << 64) - 1)
        out = np.zeros(8 * DIM, np.float32)
        # Three DISTINCT blocks (a repeated block would be served from
        # the cache after its first fill, never advancing the rr
        # rotation): rr lands one block on each chain member, so at
        # least two fills come from replicas — and ALL of them must be
        # recorded under the primary's identity.
        for b in range(3):
            out[:] = 0
            w.wait(w.pull(KEYS[b * 8:(b + 1) * 8], out))
        assert w.po.metrics.counter("replica_read.spread").value > 0
        with w._hot_cache._mu:
            idents = {w._hot_cache._entries[int(k)][1]
                      for k in KEYS[:24]
                      if int(k) in w._hot_cache._entries}
        assert idents == {primary_id}, idents
        # A push bumps the primary's stamp past every cached fill —
        # the next pull must see the NEW value, not the cache.
        delta = np.ones(8 * DIM, np.float32)
        w.wait(w.push(KEYS[:8], delta))
        out[:] = 0
        w.wait(w.pull(KEYS[:8], out))
        np.testing.assert_array_equal(out.reshape(8, DIM),
                                      table[:8] + 1.0)
    finally:
        _teardown(cluster, servers, workers)


def test_elastic_join_backfills_replicated_ranges():
    """A server joining an elastic cluster owes replica state for the
    ranges whose chain it lands in: the chain_ranks recompute triggers
    an export/import backfill, after which the joiner holds bit-exact
    copies of keys it does NOT own."""
    env = {
        "PS_ELASTIC": "1",
        "PS_KV_REPLICATION": "2",
        "PS_REPLICA_READS": "1",
        "PS_REQUEST_TIMEOUT": "2.0",
        "PS_REQUEST_RETRIES": "8",
    }
    cluster = LoopbackCluster(num_workers=1, num_servers=2,
                              env_extra=env)
    cluster.start()
    servers, workers = _spin_up(cluster)
    w = workers[0]
    try:
        # Spread keys across the full space so every owner rank holds
        # some state before the join.
        span = (1 << 64) // 8
        keys = (np.arange(8, dtype=np.uint64) * np.uint64(span)
                + np.uint64(3))
        vals = np.arange(8 * DIM, dtype=np.float32) + 1.0
        w.wait(w.push(keys, vals))
        time.sleep(0.3)
        po = cluster.join_server()
        joiner = KVServer(0, postoffice=po)
        joiner.set_request_handle(KVServerDefaultHandle())
        servers.append(joiner)
        # Wait for the joiner to replicate some range it does not own:
        # its store must grow bit-exact copies via backfill (its own
        # owned range arrives via elastic migration — backfill is the
        # REPLICA debt).
        deadline = time.monotonic() + 20
        seen = False
        while time.monotonic() < deadline and not seen:
            rt = po.current_routing()
            if rt is not None:
                my = po.my_group_rank()
                active = sorted(rt.active)
                for e in rt.entries:
                    if e.owner == my:
                        continue
                    chain = chain_ranks(e.owner, 2, po.num_servers,
                                        active=active)
                    if my not in chain:
                        continue
                    got = [int(k) for k in keys
                           if e.begin <= int(k) < e.end
                           and int(k) in joiner._handle.store]
                    if got:
                        seen = True
                        break
            time.sleep(0.1)
        assert seen, "joiner never backfilled a replicated range"
        assert po.flight.events("replica_backfill")
    finally:
        _teardown(cluster, servers, workers)
