"""Fused optimizer-update kernels (Pallas, TPU).

The server-side update is the aggregation hot loop of the reference
(``KVServerDefaultHandle``, kv_app.h:430-452, executed per push).  On TPU
the update is HBM-bandwidth-bound; these kernels apply the whole optimizer
step (SGD+momentum / Adam) in **one** tiled pass over the shard with
in-place aliasing — guaranteeing the single-pass fusion rather than hoping
XLA finds it.

Layout: flat vectors are zero-padded and reshaped to ``(rows, 128)`` with
``rows`` a multiple of the 8-sublane tile, and the kernels use 2-D
``(block_rows, 128)`` BlockSpecs — rank-1 blocks and sub-(8,128) tiles
pass the interpreter but fail Mosaic lowering on real TPU hardware.
Kernels run inside ``shard_map`` (pure per-shard compute) and fall back
to the Pallas interpreter off-TPU so unit tests run on the virtual CPU
mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_LANES = 128
_SUBLANES = 8
_MAX_BLOCK_ROWS = 512  # (512, 128) fp32 block = 256 KiB per operand


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _tile_geometry(n: int):
    """(padded_len, rows, block_rows, grid) for a flat length n."""
    rows0 = -(-n // _LANES)
    block_rows = min(_MAX_BLOCK_ROWS, -(-rows0 // _SUBLANES) * _SUBLANES)
    rows = -(-rows0 // block_rows) * block_rows
    return rows * _LANES, rows, block_rows, rows // block_rows


def _to_tiles(x, padded_len: int):
    pad = padded_len - x.shape[0]
    if pad:
        x = jnp.pad(x, (0, pad))
    return x.reshape(-1, _LANES)


@functools.partial(jax.jit, static_argnames=("lr", "momentum"))
def sgd_update(store, mom, agg, lr: float = 0.01, momentum: float = 0.9):
    """One fused pass: ``mom = momentum*mom + agg; store -= lr*mom``.

    Returns ``(new_store, new_mom)``; both alias their inputs' buffers.
    """
    from jax.experimental import pallas as pl

    n = store.shape[0]
    padded, rows, block_rows, grid = _tile_geometry(n)
    store_t = _to_tiles(store, padded)
    mom_t = _to_tiles(mom, padded)
    agg_t = _to_tiles(agg, padded)

    def kernel(store_ref, mom_ref, agg_ref, out_store_ref, out_mom_ref):
        m = momentum * mom_ref[:, :] + agg_ref[:, :]
        out_mom_ref[:, :] = m
        out_store_ref[:, :] = store_ref[:, :] - lr * m

    spec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    new_store, new_mom = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(store_t.shape, store_t.dtype),
            jax.ShapeDtypeStruct(mom_t.shape, mom_t.dtype),
        ),
        grid=(grid,),
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec),
        input_output_aliases={0: 0, 1: 1},
        interpret=_use_interpret(),
    )(store_t, mom_t, agg_t)
    return new_store.reshape(-1)[:n], new_mom.reshape(-1)[:n]


@functools.partial(jax.jit, static_argnames=("lr", "eps"))
def adagrad_update(store, acc, agg, lr: float = 0.01, eps: float = 1e-8):
    """One fused Adagrad pass: ``acc += agg**2;
    store -= lr*agg/(sqrt(acc)+eps)``.

    Returns ``(new_store, new_acc)``; both alias their inputs' buffers —
    the elementwise twin of the sparse engine's row-wise variant
    (parallel/sparse.py), completing the server-optimizer family
    (kv_app.h:430-452 hot loop as one HBM pass).
    """
    from jax.experimental import pallas as pl

    n = store.shape[0]
    padded, rows, block_rows, grid = _tile_geometry(n)
    store_t = _to_tiles(store, padded)
    acc_t = _to_tiles(acc, padded)
    agg_t = _to_tiles(agg, padded)

    def kernel(store_ref, acc_ref, agg_ref, out_store_ref, out_acc_ref):
        g = agg_ref[:, :]
        a = acc_ref[:, :] + g * g
        out_acc_ref[:, :] = a
        out_store_ref[:, :] = store_ref[:, :] - lr * g / (
            jnp.sqrt(a) + eps
        )

    spec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    new_store, new_acc = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(store_t.shape, store_t.dtype),
            jax.ShapeDtypeStruct(acc_t.shape, acc_t.dtype),
        ),
        grid=(grid,),
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec),
        input_output_aliases={0: 0, 1: 1},
        interpret=_use_interpret(),
    )(store_t, acc_t, agg_t)
    return new_store.reshape(-1)[:n], new_acc.reshape(-1)[:n]


@functools.partial(jax.jit, static_argnames=("lr", "beta1", "beta2", "eps"))
def adam_update(store, m, v, agg, step, lr: float = 1e-3,
                beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8):
    """Fused Adam step: one HBM pass updating (store, m, v) in place.

    ``step`` is the 1-based step count (dynamic scalar) for bias
    correction; the correction is folded into a per-call scalar
    ``alpha_t = lr * sqrt(1-b2^t) / (1-b1^t)`` (the standard efficient
    form) so the kernel consumes only vectors plus one prefetched scalar.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = store.shape[0]
    padded, rows, block_rows, grid = _tile_geometry(n)
    store_t = _to_tiles(store, padded)
    m_t = _to_tiles(m, padded)
    v_t = _to_tiles(v, padded)
    agg_t = _to_tiles(agg, padded)

    t = jnp.asarray(step, jnp.float32)
    alpha_t = lr * jnp.sqrt(1.0 - beta2 ** t) / (1.0 - beta1 ** t)
    scalars = jnp.stack([alpha_t]).astype(jnp.float32)

    def kernel(scalar_ref, store_ref, m_ref, v_ref, agg_ref,
               out_store_ref, out_m_ref, out_v_ref):
        g = agg_ref[:, :]
        m_new = beta1 * m_ref[:, :] + (1 - beta1) * g
        v_new = beta2 * v_ref[:, :] + (1 - beta2) * g * g
        out_m_ref[:, :] = m_new
        out_v_ref[:, :] = v_new
        out_store_ref[:, :] = store_ref[:, :] - scalar_ref[0] * m_new / (
            jnp.sqrt(v_new) + eps
        )

    # Index maps receive the prefetched scalar ref as a trailing argument.
    spec = pl.BlockSpec((block_rows, _LANES), lambda i, s: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid,),
        in_specs=[spec, spec, spec, spec],
        out_specs=(spec, spec, spec),
    )
    new_store, new_m, new_v = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(store_t.shape, store_t.dtype),
            jax.ShapeDtypeStruct(m_t.shape, m_t.dtype),
            jax.ShapeDtypeStruct(v_t.shape, v_t.dtype),
        ),
        grid_spec=grid_spec,
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=_use_interpret(),
    )(scalars, store_t, m_t, v_t, agg_t)
    return (
        new_store.reshape(-1)[:n],
        new_m.reshape(-1)[:n],
        new_v.reshape(-1)[:n],
    )
