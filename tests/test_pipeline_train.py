"""PP-integrated flagship training (models/train.py::make_pp_train_step):
pipeline stages as PS key-range owners of the layer stack.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pslite_tpu.models.train import make_pp_train_step
from pslite_tpu.models.transformer import ModelConfig, init_params, loss_fn


def _mesh(shape, names):
    n = int(np.prod(shape))
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), names)


def test_pp_first_loss_matches_sequential():
    cfg = ModelConfig(vocab=32, dim=16, heads=2, layers=4)
    mesh = _mesh((4,), ("pp",))
    M, mb, T = 4, 2, 8
    step, state, tok_sharding = make_pp_train_step(
        cfg, mesh, lr=0.1, num_micro=M
    )
    rng = np.random.default_rng(0)
    inputs = rng.integers(0, cfg.vocab, size=(M, mb, T)).astype(np.int32)
    targets = (inputs + 1) % cfg.vocab
    state, loss = step(
        state,
        jax.device_put(inputs, tok_sharding),
        jax.device_put(targets, tok_sharding),
    )
    # Reference: the same init params, full batch, single device.
    params0 = init_params(jax.random.PRNGKey(0), cfg)
    want = loss_fn(
        params0,
        jnp.asarray(inputs.reshape(M * mb, T)),
        jnp.asarray(targets.reshape(M * mb, T)),
        cfg,
    )
    np.testing.assert_allclose(float(loss), float(want), rtol=2e-2)


def test_pp_loss_decreases():
    cfg = ModelConfig(vocab=16, dim=16, heads=2, layers=4)
    mesh = _mesh((4,), ("pp",))
    M, mb, T = 2, 2, 8
    step, state, tok_sharding = make_pp_train_step(
        cfg, mesh, lr=0.3, num_micro=M
    )
    rng = np.random.default_rng(1)
    inputs = rng.integers(0, cfg.vocab, size=(M, mb, T)).astype(np.int32)
    targets = (inputs + 1) % cfg.vocab
    inputs = jax.device_put(inputs, tok_sharding)
    targets = jax.device_put(targets, tok_sharding)
    losses = []
    for _ in range(8):
        state, loss = step(state, inputs, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_pp_with_dp_axis():
    cfg = ModelConfig(vocab=16, dim=16, heads=2, layers=4)
    mesh = _mesh((2, 4), ("dp", "pp"))
    M, mb, T = 2, 2, 8
    step, state, tok_sharding = make_pp_train_step(
        cfg, mesh, lr=0.15, num_micro=M
    )
    rng = np.random.default_rng(2)
    inputs = rng.integers(0, cfg.vocab, size=(2, M, mb, T)).astype(np.int32)
    targets = (inputs + 1) % cfg.vocab
    inputs = jax.device_put(inputs, tok_sharding)
    targets = jax.device_put(targets, tok_sharding)
    losses = []
    for _ in range(14):
        state, loss = step(state, inputs, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    # Tiny model at an aggressive lr oscillates; require clear net
    # progress rather than monotonicity.
    assert min(losses[7:]) < losses[0] * 0.82, losses
