"""CollectiveEngine / SparseEngine numerics on an 8-device virtual CPU mesh.

Validates that the ICI data plane reproduces the reference's server
aggregation semantics (push => sum across workers, pull => broadcast;
kv_app.h:430-452) as jitted reduce-scatter/all-gather collectives.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pslite_tpu.parallel import CollectiveEngine, default_mesh
from pslite_tpu.parallel.sparse import SparseEngine


@pytest.fixture(scope="module")
def mesh():
    m = default_mesh()
    assert m.shape["kv"] == 8, "conftest must provide 8 virtual devices"
    return m


def test_dense_push_pull_aggregates(mesh):
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(4, dtype=np.uint64)
    val_len = 100  # total 400, not divisible by 8 -> exercises padding
    eng.register_dense("b0", keys, val_len)
    W = eng.num_shards
    base = np.arange(4 * val_len, dtype=np.float32)
    grads = np.stack([(w + 1) * base for w in range(W)])  # [W, total]
    pulled = np.asarray(eng.push_pull("b0", grads))
    expected = base * sum(range(1, W + 1))
    np.testing.assert_allclose(pulled, expected, rtol=1e-5)


def test_dense_push_accumulates_then_pull(mesh):
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(3, dtype=np.uint64)
    eng.register_dense("b1", keys, 64)
    ones = np.ones(3 * 64, dtype=np.float32)
    eng.push("b1", ones)  # broadcast to all 8 workers -> sum = 8
    eng.push("b1", ones)
    out = np.asarray(eng.pull("b1"))
    np.testing.assert_allclose(out, 16 * ones)


def test_dense_sgd_handle(mesh):
    eng = CollectiveEngine(mesh=mesh, server_handle="sgd:0.5")
    keys = np.arange(2, dtype=np.uint64)
    init = np.full(2 * 8, 10.0, dtype=np.float32)
    eng.register_dense("b2", keys, 8, init=init)
    grads = np.ones((8, 16), dtype=np.float32)  # sum = 8
    pulled = np.asarray(eng.push_pull("b2", grads))
    np.testing.assert_allclose(pulled, 10.0 - 0.5 * 8.0 * np.ones(16))


def test_dense_init_roundtrip(mesh):
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(5, dtype=np.uint64)
    init = np.random.default_rng(1).normal(size=5 * 32).astype(np.float32)
    eng.register_dense("b3", keys, 32, init=init)
    np.testing.assert_allclose(np.asarray(eng.pull("b3")), init, rtol=1e-6)


def test_sparse_push_pull(mesh):
    eng = SparseEngine(mesh)
    rng = np.random.default_rng(7)
    num_rows, dim, n = 37, 4, 6
    eng.register_sparse("emb", num_rows, dim)
    W = eng.num_shards
    # Skewed indices with duplicates within and across workers.
    idx = rng.integers(0, num_rows, size=(W, n)).astype(np.int32)
    idx[:, 0] = 3  # hot row pushed by every worker
    grads = rng.normal(size=(W, n, dim)).astype(np.float32)

    eng.push("emb", idx, grads)

    # Host reference: scatter-add.
    ref = np.zeros((num_rows, dim), dtype=np.float32)
    for w in range(W):
        for i in range(n):
            ref[idx[w, i]] += grads[w, i]

    pulled = np.asarray(eng.pull("emb", idx))  # [W, n, dim]
    for w in range(W):
        np.testing.assert_allclose(pulled[w], ref[idx[w]], rtol=1e-4,
                                   atol=1e-5)


def test_sparse_pull_zero_init(mesh):
    eng = SparseEngine(mesh)
    eng.register_sparse("z", 16, 2)
    idx = np.zeros((8, 3), dtype=np.int32)
    out = np.asarray(eng.pull("z", idx))
    assert out.shape == (8, 3, 2)
    np.testing.assert_array_equal(out, 0)
