"""Fused ring push_pull kernel (Pallas, TPU): reduce-scatter + server
update + all-gather in ONE kernel over the ICI ring.

The XLA path of :class:`~pslite_tpu.parallel.engine.CollectiveEngine`
lowers ``push_pull`` to three ops (``psum_scatter`` → handle →
``all_gather``): the reduced shard and the updated shard each make an HBM
round trip between ops, and the all-gather cannot start until the whole
update finishes.  This kernel is the TPU-native analog of the reference's
steady-state one-sided RDMA pipeline (rdma_transport.h:323-357 — data
WRITE + meta WRITE_WITH_IMM per hop, no intermediate copies): a single
ring program per device where

1. each reduce-scatter hop DMAs a chunk to the right neighbor's VMEM and
   accumulates the incoming chunk (compute overlapped with the wire),
2. the server handle (``KVServerDefaultHandle`` semantics,
   kv_app.h:430-452) is applied in VMEM the moment the owned chunk's sum
   completes — no HBM round trip, and
3. the updated chunk immediately re-enters the ring as the all-gather
   payload while later chunks are still reducing.

Flow control: two communication slots per device with credit semaphores —
a sender may reuse slot ``k`` only after the receiver signals that it has
consumed the previous payload in ``k`` (the ring neighbors otherwise have
no back-pressure and a fast sub-ring could clobber an unread slot; the
reference's AddressPool plays the same role for RDMA imm slots,
van_common.h:72-122).

Off-TPU the kernel runs under the Pallas TPU interpreter so the unit
tests exercise the full semaphore/DMA protocol on the virtual CPU mesh.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

_LANES = 128
_SUBLANES = 8
_TILE = _LANES * _SUBLANES  # minimum chunk granularity (floats)

def derive_collective_id(*key_parts) -> int:
    """Deterministic collective_id in [1, 31] for a ring program.

    Concurrently dispatched collective kernels sharing an id share the
    global barrier semaphore, so distinct programs should get distinct
    ids.  The id must ALSO be identical for the same logical program in
    every process of a multi-process mesh (each process compiles its own
    copy; mismatched ids would pair mismatched barrier semaphores across
    devices) — hence a stable hash of the program key rather than a
    process-local counter.  Collisions degrade to a shared barrier
    semaphore, which stays correct under the engine's consistent
    dispatch ordering — never incorrect, only less isolated."""
    import zlib

    text = "|".join(str(p) for p in key_parts)
    return 1 + (zlib.crc32(text.encode()) % 31)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def ring_chunk_len(total_len: int, num_devices: int, dtype=None) -> int:
    """Per-device chunk length (elements) the kernel will use for a
    bucket of ``total_len`` elements: ceil to the VMEM tile — (8, 128)
    for 4-byte dtypes, (16, 128) for 2-byte (bf16) sublane packing."""
    tile = _TILE
    if dtype is not None and jnp.dtype(dtype).itemsize == 2:
        tile = 2 * _TILE
    chunk = -(-total_len // num_devices)
    return -(-chunk // tile) * tile


def _kernel_body(n: int, axis_name: str, handle: Callable):
    """Build the unrolled kernel for a static ring size ``n``.

    Refs (per device d):
      grads_ref   ANY  [n*rows, 128] — my worker row, n chunks
      store_ref   VMEM [rows, 128]   — my store shard (chunk d)
      out_store   VMEM [rows, 128]
      out_pulled  ANY  [n*rows, 128] — replicated result
      send_buf    VMEM [rows, 128]
      recv_buf    VMEM [2, rows, 128]
      gchunk      VMEM [rows, 128]   — staging for grads chunks
      send_sem/recv_sem  DMA((2,))
      cap_sem     REGULAR((2,))      — credits from my right neighbor
      local_sem   DMA(())            — HBM<->VMEM staging copies
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(grads_ref, store_ref, out_store_ref, out_pulled_ref,
               send_buf, recv_buf, gchunk, send_sem, recv_sem, cap_sem,
               local_sem):
        d = lax.axis_index(axis_name)
        right = lax.rem(d + 1, n)
        left = lax.rem(d + n - 1, n)
        rows = store_ref.shape[0]

        # Ring-entry barrier: a fast neighbor must not DMA into our
        # scratch before this invocation owns it.
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

        def stage_grads_chunk(chunk_idx):
            """DMA grads chunk ``chunk_idx`` (dynamic) HBM -> gchunk."""
            cp = pltpu.make_async_copy(
                grads_ref.at[pl.ds(chunk_idx * rows, rows)],
                gchunk,
                local_sem,
            )
            cp.start()
            cp.wait()

        def write_pulled(chunk_idx, src_ref):
            cp = pltpu.make_async_copy(
                src_ref,
                out_pulled_ref.at[pl.ds(chunk_idx * rows, rows)],
                local_sem,
            )
            cp.start()
            cp.wait()

        def send_step(t: int):
            """DMA send_buf into the right neighbor's recv slot t%2."""
            if t >= 2:
                # Credit: my right neighbor freed its slot t%2 (from t-2).
                pltpu.semaphore_wait(cap_sem.at[t % 2], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=send_buf,
                dst_ref=recv_buf.at[t % 2],
                send_sem=send_sem.at[t % 2],
                recv_sem=recv_sem.at[t % 2],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()

        def free_slot(k: int):
            """Tell my LEFT neighbor its outgoing slot k is consumable."""
            pltpu.semaphore_signal(
                cap_sem.at[k], inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.LOGICAL)

        # ---- phase 1: ring reduce-scatter (steps 0..n-2) ----------------
        # At step t, send chunk (d + n-1-t) % n; for t>0 that is the chunk
        # received at t-1 plus my own contribution.  After step n-2 the
        # chunk received last is (d+1... ) such that my OWNED chunk is d.
        for t in range(n - 1):
            c_t = lax.rem(d + n - 1 - t, n)
            stage_grads_chunk(c_t)
            if t == 0:
                send_buf[...] = gchunk[...]
            else:
                send_buf[...] = recv_buf[(t - 1) % 2] + gchunk[...]
                free_slot((t - 1) % 2)
            send_step(t)

        # ---- boundary: own chunk complete -> apply the server handle ----
        stage_grads_chunk(d)
        if n >= 2:
            summed = recv_buf[(n - 2) % 2] + gchunk[...]
            free_slot((n - 2) % 2)
        else:
            summed = gchunk[...]
        updated = handle(store_ref[...], summed)
        out_store_ref[...] = updated
        write_pulled(d, out_store_ref)

        # ---- phase 2: ring all-gather of updated chunks -----------------
        # AG step s2 (global t = n-1+s2): send chunk (d - s2) % n; s2=0
        # sends my freshly updated chunk, later steps forward what arrived.
        for s2 in range(n - 1):
            t = n - 1 + s2
            if s2 == 0:
                send_buf[...] = updated
            else:
                send_buf[...] = recv_buf[(t - 1) % 2]
                write_pulled(lax.rem(d - s2 + n, n), send_buf)
                free_slot((t - 1) % 2)
            send_step(t)
        if n >= 2:
            # Final arrival: chunk (d - (n-1)) % n == (d+1) % n.
            last = 2 * (n - 1) - 1
            send_buf[...] = recv_buf[last % 2]
            write_pulled(lax.rem(d + 1, n), send_buf)
            free_slot(last % 2)
            # Drain the one un-consumed credit per slot (the credits for
            # the final sends have no matching wait) so the scratch
            # semaphores are zero at kernel exit — leftover counts would
            # poison the next collective kernel reusing them.
            pltpu.semaphore_wait(cap_sem.at[0], 1)
            pltpu.semaphore_wait(cap_sem.at[1], 1)

    return kernel


def ring_push_pull(grads_chunks, store_chunk, handle: Callable,
                   axis_name: str, num_devices: int,
                   collective_id: int = None):
    """Run the fused RS+update+AG ring inside a shard_map body.

    Args (per-device views inside shard_map):
      grads_chunks: [n, chunk] — my worker row viewed as n ring chunks
                    (``chunk`` must be a multiple of 1024 — see
                    :func:`ring_chunk_len`).
      store_chunk:  [chunk]    — my store shard.
      handle:       jittable (store_chunk, summed_grads) -> new_store
                    applied blockwise in VMEM (elementwise-safe handles
                    only: padding lanes flow through it).
    Returns (new_store_chunk [chunk], pulled [n*chunk]).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = num_devices
    chunk = store_chunk.shape[0]
    if chunk % _TILE:
        raise ValueError(f"chunk {chunk} not a multiple of {_TILE}")
    if collective_id is None:
        collective_id = derive_collective_id(
            n, chunk, str(store_chunk.dtype)
        )
    rows = chunk // _LANES
    dtype = store_chunk.dtype
    g2 = grads_chunks.reshape(n * rows, _LANES)
    s2 = store_chunk.reshape(rows, _LANES)

    kernel = _kernel_body(n, axis_name, handle)
    out_store, out_pulled = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((rows, _LANES), dtype),
            jax.ShapeDtypeStruct((n * rows, _LANES), dtype),
        ),
        in_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((rows, _LANES), dtype),       # send_buf
            pltpu.VMEM((2, rows, _LANES), dtype),    # recv_buf
            pltpu.VMEM((rows, _LANES), dtype),       # gchunk
            pltpu.SemaphoreType.DMA((2,)),           # send_sem
            pltpu.SemaphoreType.DMA((2,)),           # recv_sem
            pltpu.SemaphoreType.REGULAR((2,)),       # cap_sem
            pltpu.SemaphoreType.DMA,                 # local_sem
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=(pltpu.InterpretParams() if _use_interpret() else False),
    )(g2, s2)
    return out_store.reshape(chunk), out_pulled.reshape(n * chunk)
