"""Elastic end-to-end: crash -> keepalive restart -> dead-id recovery ->
cluster continues and finalizes cleanly.

Exercises the full reliability chain in one scenario: heartbeats
(PS_HEARTBEAT_*), scheduler dead-node detection, recovery id inheritance,
launcher keepalive (exit 254), and continued KV traffic afterwards —
the reference's recovery story (van.cc:266-332 + dmlc_local.py keepalive)
driven through real OS processes.  crashes=2 re-inherits the dead id
twice, proving recovery bookkeeping survives repetition.
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.parametrize("crashes", [1, 2])
def test_worker_crash_recovery_end_to_end(tmp_path, crashes):
    marker = tmp_path / "crashed"
    child = os.path.join(os.path.dirname(__file__), "elastic_child.py")
    env = dict(
        os.environ,
        PS_HEARTBEAT_INTERVAL="1",
        PS_HEARTBEAT_TIMEOUT="2",
        PS_ELASTIC_CRASHES=str(crashes),
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "pslite_tpu.tracker.local",
            "-n", "2", "-s", "1", "--",
            sys.executable, child, str(marker),
        ],
        capture_output=True,
        timeout=300 + 120 * crashes,
        env=env,
        cwd="/root/repo",
    )
    out = proc.stdout.decode() + proc.stderr.decode()
    assert proc.returncode == 0, out[-3000:]
    assert marker.read_text().strip() == str(crashes)
    assert out.count("restarting worker (exit 254)") == crashes
    assert "RECOVERED_OK" in out
    assert "POLL_OK" in out
    # Every role's FINAL life finalized cleanly (scheduler, server, 2 workers).
    assert out.count("ELASTIC_DONE") == 4, out[-3000:]
