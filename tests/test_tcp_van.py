"""TCP van tests: in-process cluster over real sockets, plus a true
multi-process cluster (the reference's tests/local.sh pattern)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.utils.network import get_available_port

from helpers import LoopbackCluster


@pytest.mark.parametrize("cores,expect_native", [(1, False), (4, True)])
def test_native_auto_select_by_core_count(monkeypatch, cores,
                                          expect_native):
    """Default PS_NATIVE=auto picks the winner for the host: pure
    Python on single-core (PARITY 2b: the GIL-free io threads lose
    1.3-1.9x with no spare core), the native core when cores allow."""
    from pslite_tpu.vans import native as native_mod

    if native_mod.load() is None:
        pytest.skip("native core not built")
    monkeypatch.setattr("pslite_tpu.vans.tcp_van.os.sched_getaffinity",
                        lambda pid: set(range(cores)))
    cluster = LoopbackCluster(num_workers=1, num_servers=1,
                              van_type="tcp")
    cluster.start()
    try:
        van = cluster.servers[0].van
        assert (van._native is not None) == expect_native
    finally:
        cluster.finalize()


def test_tcp_cluster_in_process():
    cluster = LoopbackCluster(num_workers=2, num_servers=2, van_type="tcp")
    cluster.start()
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        w0 = KVWorker(0, 0, postoffice=cluster.workers[0])
        w1 = KVWorker(0, 0, postoffice=cluster.workers[1])

        ranges = cluster.workers[0].get_server_key_ranges()
        keys = np.array(
            sorted([ranges[0].begin + 3, ranges[1].begin + 7]), dtype=np.uint64
        )
        k = 1024
        vals = np.linspace(0, 1, 2 * k).astype(np.float32)
        w0.wait(w0.push(keys, vals))
        w1.wait(w1.push(keys, vals))
        out = np.zeros_like(vals)
        w1.wait(w1.pull(keys, out))
        np.testing.assert_allclose(out, 2 * vals, rtol=1e-6)
    finally:
        for srv in servers:
            srv.stop()
        cluster.finalize()


def test_tcp_cluster_pure_python_fallback():
    """PS_NATIVE=0 must keep the socket path working (hosts without the
    built C++ core)."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="tcp",
        env_extra={"PS_NATIVE": "0"},
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([1], dtype=np.uint64)
        vals = np.arange(128, dtype=np.float32)
        w.wait(w.push(keys, vals))
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        np.testing.assert_allclose(out, vals)
        assert cluster.workers[0].van._native is None
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_tcp_cluster_multiprocess():
    """1 scheduler + 2 servers + 2 workers as separate OS processes."""
    port = get_available_port()
    child = os.path.join(os.path.dirname(__file__), "tcp_child.py")
    base_env = dict(
        os.environ,
        DMLC_NUM_WORKER="2",
        DMLC_NUM_SERVER="2",
        DMLC_PS_ROOT_URI="127.0.0.1",
        DMLC_PS_ROOT_PORT=str(port),
        DMLC_NODE_HOST="127.0.0.1",
        PS_VAN_TYPE="tcp",
        PS_VERBOSE="1",  # a hung child's dump then shows barrier progress
    )
    procs = []
    for role in ["scheduler", "server", "server", "worker", "worker"]:
        env = dict(base_env, DMLC_ROLE=role)
        procs.append(
            subprocess.Popen(
                [sys.executable, child],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    outputs = []
    for p in procs:
        try:
            # Generous: this 1-CPU host serializes 5 interpreter startups,
            # and cold-cache runs add jit compilation elsewhere in the suite.
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outputs.append(out.decode())
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"child failed:\n{out}"
    worker_outs = [o for o in outputs if "WORKER_OK" in o]
    assert len(worker_outs) == 2, f"expected 2 worker OKs, got: {outputs}"


def _run_local_mode_cluster(env_extra):
    """DMLC_LOCAL=1: the whole cluster rides unix-domain sockets
    (the reference's ipc:///tmp/<port> mode, zmq_van.h:107-115)."""
    from pslite_tpu.vans.tcp_van import _local_sock_path

    cluster = LoopbackCluster(
        num_workers=2, num_servers=1, van_type="tcp", env_extra=env_extra,
    )
    cluster.start()
    servers = []
    try:
        # The advertised ports must map to live unix-socket files.
        for po in list(cluster.servers) + list(cluster.workers):
            path = _local_sock_path(po.van.my_node.port)
            assert os.path.exists(path), f"no unix socket at {path}"

        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w0 = KVWorker(0, 0, postoffice=cluster.workers[0])
        w1 = KVWorker(0, 0, postoffice=cluster.workers[1])
        keys = np.array([5, 9], dtype=np.uint64)
        vals = np.arange(256, dtype=np.float32)
        w0.wait(w0.push(keys, vals))
        w1.wait(w1.push(keys, vals))
        out = np.zeros_like(vals)
        w0.wait(w0.pull(keys, out))
        np.testing.assert_allclose(out, 2 * vals, rtol=1e-6)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()
    # Sockets are unlinked on shutdown (stale ipc files are the classic
    # zmq ipc:// footgun the van must not reproduce).
    leftovers = [
        p
        for po in list(cluster.servers) + list(cluster.workers)
        for p in [_local_sock_path(po.van.my_node.port)]
        if os.path.exists(p)
    ]
    assert not leftovers, f"stale unix sockets: {leftovers}"


def test_dmlc_local_unix_sockets_native():
    _run_local_mode_cluster({"DMLC_LOCAL": "1"})


def test_dmlc_local_unix_sockets_pure_python():
    _run_local_mode_cluster({"DMLC_LOCAL": "1", "PS_NATIVE": "0"})


def test_dmlc_local_reclaims_stale_socket():
    """A crashed run's leftover socket file must not wedge the next
    cluster: bind probes the path and reclaims it when nothing listens."""
    import socket

    from pslite_tpu.vans.tcp_van import _local_sock_path

    port = get_available_port()
    stale = _local_sock_path(port)
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.bind(stale)
    s.close()  # file remains, no listener — the crash signature
    assert os.path.exists(stale)
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="tcp",
        env_extra={"DMLC_LOCAL": "1", "DMLC_PS_ROOT_PORT": str(port)},
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([3], dtype=np.uint64)
        vals = np.ones(64, np.float32)
        w.wait(w.push(keys, vals))
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        np.testing.assert_allclose(out, vals)
    finally:
        for s2 in servers:
            s2.stop()
        cluster.finalize()


def test_send_failure_redials():
    """Transport-level reconnect (the UCX van's error-handler redial):
    a send hitting a broken connection reconnects to the last-known
    address and retries, invisibly to the app."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="tcp",
        env_extra={"PS_NATIVE": "0", "PS_RECONNECT_TMO": "10"},
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([7], dtype=np.uint64)
        vals = np.ones(128, np.float32)
        w.wait(w.push(keys, vals))

        # Break the worker's connection to the server out from under it.
        van = cluster.workers[0].van
        server_id = cluster.servers[0].van.my_node.id
        with van._socks_mu:
            broken = van._send_socks[server_id]
        broken.close()

        # The next push rides the redial path transparently.
        w.wait(w.push(keys, vals))
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        np.testing.assert_allclose(out, 2 * vals)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


@pytest.mark.parametrize("native", ["1", "0"])
def test_corrupt_frame_does_not_kill_cluster(native):
    """A malformed frame from a rogue connection must not kill the
    receive pump (native path: frame dropped; python path: connection
    dropped) — the cluster keeps serving."""
    import socket
    import struct
    import time

    from pslite_tpu import wire

    if native == "1":
        from pslite_tpu.vans import native as native_mod

        if native_mod.load() is None:
            pytest.skip("native core not built — the Van-level continue "
                        "path would go untested")
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="tcp",
        env_extra={"PS_NATIVE": native},
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([2], dtype=np.uint64)
        vals = np.ones(64, np.float32)
        w.wait(w.push(keys, vals))

        # Rogue connection injects a well-framed but undecodable meta.
        port = cluster.servers[0].van.my_node.port
        rogue = socket.create_connection(("127.0.0.1", port), timeout=10)
        garbage = b"\xde\xad\xbe\xef" * 4
        rogue.sendall(
            struct.pack("<III", wire.MAGIC, len(garbage), 0) + garbage
        )
        time.sleep(0.5)  # let the server's pump chew on it
        rogue.close()

        # The server must still serve KV traffic afterwards.
        w.wait(w.push(keys, vals))
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        np.testing.assert_allclose(out, 2 * vals)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


@pytest.mark.parametrize("native", ["1", "0"])
def test_registered_recv_buffer_transport_delivery_tcp(native):
    """The TCP van delivers registered pushes in place (zmq_van.h:
    206-218, 243-263 analog): pure-Python readers recv_into the
    registered buffer directly off the socket; the native path places at
    the deliver hook.  Either way KVServer.delivered_in_place counts."""
    from pslite_tpu import KVServer

    if native == "1":
        from pslite_tpu.vans import native as native_mod

        if native_mod.load() is None:
            pytest.skip("native core not built")
    cluster = LoopbackCluster(num_workers=1, num_servers=1,
                              van_type="tcp",
                              env_extra={"PS_NATIVE": native})
    cluster.start()
    servers = []
    try:
        seen = {}

        def handle(meta, data, server):
            if meta.push:
                seen["vals"] = data.vals
            server.response(meta)

        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(handle)
        servers.append(srv)

        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        worker_id = cluster.workers[0].van.my_node.id
        registered = np.zeros(4096, dtype=np.float32)
        srv.register_recv_buffer(worker_id, 7, registered)

        vals = np.arange(4096, dtype=np.float32)
        worker.wait(worker.push(np.array([7], np.uint64), vals))
        assert "vals" in seen
        assert np.shares_memory(seen["vals"], registered)
        np.testing.assert_allclose(registered, vals)
        assert srv.delivered_in_place == 1, srv.delivered_in_place

        # Second push into the same buffer (segment-reuse contract).
        worker.wait(worker.push(np.array([7], np.uint64), 2 * vals))
        np.testing.assert_allclose(registered, 2 * vals)
        assert srv.delivered_in_place == 2
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()
