"""Elastic membership (docs/elasticity.md): live server join/leave with
key-range migration, request parking, and wrong-owner re-routes.

Covers the tentpole protocol end to end over in-process loopback
clusters — the versioned routing table, elastic ADD_NODE admission,
graceful REMOVE_NODE decommission, migration bit-exactness under a
concurrent push storm, OPT_WRONG_OWNER re-routing with a deliberately
stale worker, the hot-cache invalidation satellite, the replication
tenant-label satellite, and psmon's epoch/membership view — plus the
chaos acceptance (drop/delay/dup + a concurrent server crash during a
live migration) as a slow-marked storm.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from helpers import LoopbackCluster  # noqa: E402

from pslite_tpu.base import server_rank_to_id  # noqa: E402
from pslite_tpu.kv.kv_app import (  # noqa: E402
    KVServer,
    KVServerDefaultHandle,
    KVWorker,
)
from pslite_tpu.routing import RouteEntry, RoutingTable  # noqa: E402

ELASTIC_ENV = {
    "PS_ELASTIC": "1",
    "PS_REQUEST_TIMEOUT": "2.0",
    "PS_REQUEST_RETRIES": "8",
}


def _spin_up(cluster):
    servers = []
    for po in cluster.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    workers = [KVWorker(0, 0, postoffice=po) for po in cluster.workers]
    return servers, workers


def _join(cluster, servers, env_extra=None):
    po = cluster.join_server(env_extra)
    srv = KVServer(0, postoffice=po)
    srv.set_request_handle(KVServerDefaultHandle())
    servers.append(srv)
    return po, srv


def _teardown(cluster, servers, workers):
    for w in workers:
        w.stop()
    for s in servers:
        s.stop()
    for po in cluster.all_nodes():
        try:
            po.van.stop()
        except Exception:  # noqa: BLE001 - already stopped
            pass


def _wait_epoch(po, epoch, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rt = po.current_routing()
        if rt is not None and rt.epoch >= epoch:
            return rt
        time.sleep(0.02)
    raise TimeoutError(f"node never reached routing epoch {epoch}")


def _spread_keys(n):
    span = (1 << 64) // n
    return (np.arange(n, dtype=np.uint64) * np.uint64(span)
            + np.uint64(3))


# -- routing table unit ------------------------------------------------------


def test_routing_table_transitions():
    t0 = RoutingTable.initial(2)
    assert t0.epoch == 0 and t0.active == (0, 1)
    # Epoch 0 must equal the static uniform split.
    assert [e.begin for e in t0.entries] == [0, (2**64 - 1) // 2]
    t1 = t0.with_join(2)
    assert t1.epoch == 1 and 2 in t1.active
    migs = t1.migrations()
    assert len(migs) == 1 and migs[0].owner == 2
    # Coverage stays contiguous and total.
    es = sorted(t1.entries, key=lambda e: e.begin)
    assert es[0].begin == 0 and es[-1].end == 2**64 - 1
    for a, b in zip(es, es[1:]):
        assert a.end == b.begin
    # Load-weighted split: the hot range is the one divided, at its
    # median hot key.
    hot = {5: 100, 7: 90, 11: 80}
    t2 = t1.with_join(3, hot=hot)
    m = t2.migrations()[0]
    assert m.owner == 3 and m.begin == 7  # median of {5, 7, 11}
    # Leave: ranges reassign to an adjacent owner, rank marked leaving.
    t3 = t2.with_leave(2)
    assert 2 in t3.leaving and all(e.owner != 2 for e in t3.entries)
    assert all(e.prev == 2 for e in t3.migrations())
    t4 = t3.with_departed(2)
    assert 2 not in t4.active and 2 not in t4.leaving
    rt = RoutingTable.from_json(t4.to_json())
    assert rt == t4
    with pytest.raises(Exception):
        t4.with_leave(99)  # not a member


def test_hot_cache_invalidate_range_unit():
    from pslite_tpu.kv.hot_cache import HotKeyCache

    cache = HotKeyCache(max_bytes=1 << 20, ttl_s=60.0)
    keys = np.array([10, 20, 30], dtype=np.uint64)
    vals = np.arange(12, dtype=np.float32)
    cache.fill(8, 1, keys, vals)
    assert len(cache) == 3
    assert cache.invalidate_range(15, 25) == 1  # drops key 20 only
    out = np.zeros(4, np.float32)
    assert not cache.serve(np.array([20], dtype=np.uint64), out)
    assert cache.serve(np.array([10], dtype=np.uint64), out)


# -- live join / leave -------------------------------------------------------


def test_join_migrates_then_decommission_merges_back():
    """A server joins the RUNNING cluster: the scheduler splits a
    range toward it, the donor migrates the range's state live, and
    pulls keep answering correctly; a graceful decommission migrates
    everything back and retires the rank."""
    cluster = LoopbackCluster(num_workers=1, num_servers=2,
                              env_extra=dict(ELASTIC_ENV))
    cluster.start()
    servers, workers = _spin_up(cluster)
    worker = workers[0]
    keys = _spread_keys(8)
    vals = np.ones(8 * 32, np.float32)
    try:
        for _ in range(4):
            worker.wait(worker.push(keys, vals))
        jpo, jsrv = _join(cluster, servers)
        assert jpo.elastic_join and jpo.is_recovery
        rt = _wait_epoch(cluster.workers[0], 1)
        assert sorted(rt.active) == [0, 1, 2]
        # Pulls during/after the handoff stay correct (parking at the
        # new owner, never a silent miss or stale value).
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        np.testing.assert_array_equal(out, vals * 4)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not jsrv._handle.store:
            time.sleep(0.02)
        assert jsrv._handle.store, "no keys migrated to the joiner"
        for _ in range(3):
            worker.wait(worker.push(keys, vals))
        # psmon renders the epoch + membership view from the snapshot.
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools"))
        import psmon

        snap = psmon.collect(cluster.scheduler)
        table = psmon.format_table(snap)
        assert "epoch" in table and "elastic membership" in table
        assert any("owns" in ln for ln in table.splitlines())
        # Graceful leave: everything flows back, rank 2 retires.
        jsrv.decommission(timeout_s=30)
        rt = _wait_epoch(cluster.workers[0], 3)
        assert sorted(rt.active) == [0, 1]
        assert not jsrv._handle.store  # local copy dropped after ack
        worker.wait(worker.push(keys, vals))
        out2 = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out2))
        np.testing.assert_array_equal(out2, vals * 8)
    finally:
        _teardown(cluster, servers, workers)


def test_wrong_owner_bounce_reroutes_and_self_heals():
    """A worker with a STALE routing table sends to the old owner: the
    server bounces with OPT_WRONG_OWNER (nothing applied), the worker
    pulls the current table from the scheduler and the sweeper
    re-routes — the wait completes, the write lands exactly once at
    the new owner."""
    cluster = LoopbackCluster(num_workers=1, num_servers=2,
                              env_extra=dict(ELASTIC_ENV))
    cluster.start()
    servers, workers = _spin_up(cluster)
    worker = workers[0]
    key = np.array([2**63 + 77], dtype=np.uint64)  # rank 1's range
    vals = np.ones(16, np.float32)
    try:
        worker.wait(worker.push(key, vals))
        # Doctor a newer epoch onto the scheduler + servers ONLY: every
        # rank-1 range flips to rank 0 with no migration markers (the
        # state is moved by hand below) — isolating the bounce +
        # re-route + table-pull path from the migration machinery.
        base = cluster.scheduler.routing_table()
        doctored = RoutingTable(
            epoch=base.epoch + 1, num_servers=2, active=(0, 1),
            entries=tuple(
                RouteEntry(e.begin, e.end,
                           0 if e.owner == 1 else e.owner)
                for e in base.entries
            ),
        )
        r0 = next(s for s in servers
                  if s.po.van.my_node.id == server_rank_to_id(0))
        r1 = next(s for s in servers
                  if s.po.van.my_node.id == server_rank_to_id(1))
        for k, v in list(r1._handle.store.items()):
            r0._handle.store[k] = v.copy()
        cluster.scheduler.apply_routing(doctored)
        for s in (r0, r1):
            s.po.apply_routing(doctored)
        # The worker still holds the old epoch: its next push goes to
        # rank 1, bounces, re-routes to rank 0, and completes.
        worker.wait(worker.push(key, vals))
        assert worker.po.metrics.counter(
            "kv.wrong_owner_bounces").value >= 1
        assert r1._c_wrong_owner.value >= 1
        rt = _wait_epoch(cluster.workers[0], doctored.epoch)
        assert rt.epoch >= doctored.epoch  # pulled from the scheduler
        out = np.zeros_like(vals)
        worker.wait(worker.pull(key, out))
        np.testing.assert_array_equal(out, vals * 2)  # exactly once
    finally:
        _teardown(cluster, servers, workers)


def test_scale_2_4_2_mid_storm_bitexact():
    """The acceptance storm: scale 2 -> 4 -> 2 servers in the middle
    of a continuous push storm — no global restart, every wait()
    completes, and the final store is BIT-exact with a fault-free run
    (= completed pushes x payload)."""
    cluster = LoopbackCluster(num_workers=1, num_servers=2,
                              env_extra=dict(ELASTIC_ENV))
    cluster.start()
    servers, workers = _spin_up(cluster)
    worker = workers[0]
    keys = _spread_keys(32)
    vals = (np.arange(32 * 64, dtype=np.float32) % 17) + 1.0
    pushes = [0]
    stop = [False]
    errors = []

    def storm():
        while not stop[0]:
            try:
                worker.wait(worker.push(keys, vals))
                pushes[0] += 1
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))
                return

    try:
        t = threading.Thread(target=storm, daemon=True)
        t.start()
        time.sleep(0.2)
        joiner_srvs = []
        for _ in range(2):
            _jpo, jsrv = _join(cluster, servers)
            joiner_srvs.append(jsrv)
            time.sleep(0.3)
        _wait_epoch(cluster.workers[0], 2)
        time.sleep(0.3)
        for jsrv in joiner_srvs:
            jsrv.decommission(timeout_s=30)
        _wait_epoch(cluster.workers[0], 6)
        time.sleep(0.2)
        stop[0] = True
        t.join(timeout=20)
        assert not t.is_alive(), "storm wedged"
        assert not errors, errors
        n = pushes[0]
        assert n > 0
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        np.testing.assert_array_equal(out, vals * n)
        rt = cluster.workers[0].current_routing()
        assert sorted(rt.active) == [0, 1]
        for jsrv in joiner_srvs:
            assert not jsrv._handle.store
    finally:
        stop[0] = True
        _teardown(cluster, servers, workers)


# -- satellites --------------------------------------------------------------


def test_hot_cache_invalidated_when_owner_changes_epoch():
    """A migrated key must not be served from a stamp minted by its
    old owner: the worker's routing hook drops cached entries of every
    range that changed hands."""
    env = dict(ELASTIC_ENV)
    env.update({"PS_HOT_CACHE": "1", "PS_HOT_CACHE_TTL_S": "60"})
    cluster = LoopbackCluster(num_workers=2, num_servers=2,
                              env_extra=env)
    cluster.start()
    servers, workers = _spin_up(cluster)
    w1, w2 = workers
    keys = _spread_keys(8)
    vals = np.ones(8 * 4, np.float32)
    try:
        w1.wait(w1.push(keys, vals))
        out = np.zeros_like(vals)
        w1.wait(w1.pull(keys, out))  # fills w1's cache
        w1.wait(w1.pull(keys, out))
        assert w1.hot_cache is not None and len(w1.hot_cache) > 0
        before = len(w1.hot_cache)
        _jpo, jsrv = _join(cluster, servers)
        rt = _wait_epoch(cluster.workers[0], 1)
        moved = rt.migrations()[0]
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not jsrv._handle.store:
            time.sleep(0.02)
        in_moved = [int(k) for k in keys
                    if moved.begin <= int(k) < moved.end]
        assert in_moved, "split produced no moved test keys"
        # Entries of the migrated range were dropped by the hook.
        assert len(w1.hot_cache) < before
        # Another worker pushes through the NEW owner; w1's next pull
        # of the moved key must fetch the fresh value, never a stale
        # old-owner-stamped cache fill.
        w2.wait(w2.push(keys, vals))
        got = np.zeros(4, np.float32)
        w1.wait(w1.pull(np.array(in_moved[:1], dtype=np.uint64), got))
        np.testing.assert_array_equal(got, np.full(4, 2.0, np.float32))
    finally:
        _teardown(cluster, servers, workers)


def test_replication_forward_carries_tenant_label():
    """Replication forwards carry the originating tenant's EXT_QOS
    label: replica-side per-tenant metrics see the TRUE tenant (PR 8
    follow-up)."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=2,
        env_extra={"PS_KV_REPLICATION": "2",
                   "PS_TENANTS": "serve:8,train:1"},
    )
    cluster.start()
    servers, workers = _spin_up(cluster)
    worker = workers[0]
    key = np.array([5], dtype=np.uint64)  # rank 0's range
    try:
        worker.wait(worker.push(key, np.ones(8, np.float32),
                                tenant="serve"))
        replica = next(s for s in servers
                       if s.po.van.my_node.id == server_rank_to_id(1))
        deadline = time.monotonic() + 10
        counter = None
        while time.monotonic() < deadline:
            snap = replica.po.metrics.snapshot()
            counter = snap.get("counters", {}).get(
                "tenant.serve.requests")
            if counter:
                break
            time.sleep(0.05)
        assert counter and counter >= 1, (
            "replica never accounted the forward to tenant 'serve'"
        )
    finally:
        _teardown(cluster, servers, workers)


@pytest.mark.slow
def test_chaos_migration_with_crash_bitexact():
    """Chaos acceptance (docs/elasticity.md): drop/delay/dup on the
    wire PLUS a concurrent server crash while a live migration is in
    flight — every wait() completes or raises, the pump never wedges,
    and the surviving stores serve values bit-exact with a fault-free
    run."""
    chaos = "seed=11,drop=0.03,dup=0.02,delay=1:5"
    env = {
        "PS_ELASTIC": "1",
        "PS_KV_REPLICATION": "3",
        "PS_RESEND": "1",
        "PS_RESEND_TIMEOUT": "100",
        "PS_HEARTBEAT_INTERVAL": "0.2",
        "PS_HEARTBEAT_TIMEOUT": "1.0",
        "PS_REQUEST_TIMEOUT": "1.0",
        "PS_REQUEST_RETRIES": "8",
        "PS_VAN_TYPE": "chaos+loopback",
        "PS_CHAOS": chaos,
    }
    cluster = LoopbackCluster(
        num_workers=1, num_servers=3, env_extra=env,
        # The victim CRASHES (goes deaf, heartbeats stop) after ~enough
        # received messages to land mid-storm: an un-acked push is
        # retried to the replica chain (exactly-once via origin dedup),
        # so no write is ever acknowledged-but-unreplicated — a
        # graceful van.stop() would ack writes whose chain forwards
        # chaos can still drop.
        per_node_env={"server1": {"PS_CHAOS": f"{chaos},crash=recv:200"}},
    )
    cluster.start()
    servers, workers = _spin_up(cluster)
    worker = workers[0]
    keys = _spread_keys(24)
    vals = (np.arange(24 * 32, dtype=np.float32) % 13) + 1.0
    pushes = [0]
    stop = [False]
    errors = []

    def storm():
        while not stop[0]:
            try:
                worker.wait(worker.push(keys, vals))
                pushes[0] += 1
                time.sleep(0.001)  # bounded rate: crash lands mid-run
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))
                return

    victim_po = next(po for po in cluster.servers
                     if po.van.my_node.id == server_rank_to_id(1))
    try:
        t = threading.Thread(target=storm, daemon=True)
        t.start()
        time.sleep(0.3)
        _jpo, _jsrv = _join(cluster, servers)  # migration begins
        # The chaos crash hook kills the victim around here (deaf +
        # heartbeats suppressed -> the detector declares it dead).
        dead_id = server_rank_to_id(1)
        deadline = time.monotonic() + 30
        while (time.monotonic() < deadline
               and dead_id not in worker._down_servers):
            time.sleep(0.02)
        assert victim_po.van.chaos_crashed.is_set(), \
            "victim never crashed — scenario inert"
        assert dead_id in worker._down_servers, "detector never fired"
        time.sleep(0.5)
        stop[0] = True
        t.join(timeout=30)
        assert not t.is_alive(), "storm wedged (pump dead?)"
        assert not errors, errors
        n = pushes[0]
        assert n > 0
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        # Bit-exact vs fault-free: every completed push applied exactly
        # once on whatever copy now serves each range (replica failover
        # + migration parking + resend dedup compose).
        np.testing.assert_array_equal(out, vals * n)
    finally:
        stop[0] = True
        for w in workers:
            w.stop()
        for s in servers:
            if s.po is not victim_po:
                s.stop()
        for po in cluster.all_nodes():
            try:
                po.van.stop()
            except Exception:  # noqa: BLE001
                pass
