"""Fault-tolerance tier: drop injection + resender, heartbeats, recovery,
active failure detection, and bounded requests.

Mirrors the reference's reliability machinery: ``PS_DROP_MSG`` receive-side
drop injection exercising the Resender (van.cc:652-658, src/resender.h),
heartbeat-based dead-node detection (postoffice.cc:285-304), and dead-id
reassignment recovery (van.cc:266-332) — plus the ACTIVE tier this repo
adds on top (docs/fault_tolerance.md): the scheduler's failure-detector
scan + NODE_FAILURE broadcast, request deadlines surfacing TimeoutError
through ``wait``, and the resender's delivery-failure reporting.
"""

import time

import numpy as np
import pytest

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.base import SCHEDULER_ID, server_rank_to_id
from pslite_tpu.environment import Environment
from pslite_tpu.message import Role
from pslite_tpu.postoffice import Postoffice
from pslite_tpu.vans.resender import Resender

from helpers import LoopbackCluster


def test_drop_injection_with_resender():
    """30% receive-side drops must be healed by ack/retransmit."""
    cluster = LoopbackCluster(
        num_workers=1,
        num_servers=1,
        env_extra={
            "PS_DROP_MSG": "30",
            "PS_RESEND": "1",
            "PS_RESEND_TIMEOUT": "50",
        },
    )
    cluster.start()
    servers = []
    try:
        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([7], dtype=np.uint64)
        vals = np.ones(64, dtype=np.float32)
        for _ in range(5):
            worker.wait(worker.push(keys, vals))
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        np.testing.assert_allclose(out, 5 * vals)
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_heartbeat_tracking():
    cluster = LoopbackCluster(
        num_workers=1,
        num_servers=1,
        env_extra={"PS_HEARTBEAT_INTERVAL": "1"},
    )
    cluster.start()
    try:
        time.sleep(2.5)
        # Scheduler has seen recent heartbeats from both nodes.
        assert cluster.scheduler.get_dead_nodes(timeout_s=60) == []
        hb = cluster.scheduler._heartbeats
        assert set(hb) >= {8, 9}
    finally:
        cluster.finalize()


def test_dead_node_detection_and_recovery():
    cluster = LoopbackCluster(
        num_workers=1,
        num_servers=2,
        env_extra={
            "PS_HEARTBEAT_INTERVAL": "1",
            "PS_HEARTBEAT_TIMEOUT": "2",
        },
    )
    cluster.start()
    try:
        victim = next(
            po for po in cluster.servers
            if po.van.my_node.id == server_rank_to_id(1)
        )
        victim.van.stop()  # simulate a crash (no finalize barrier)
        time.sleep(3.5)
        dead = cluster.scheduler.get_dead_nodes(timeout_s=2)
        assert server_rank_to_id(1) in dead

        # A replacement registers and inherits the dead id.
        env = Environment(dict(cluster.base_env,
                               PS_HEARTBEAT_INTERVAL="1",
                               PS_HEARTBEAT_TIMEOUT="2"))
        replacement = Postoffice(Role.SERVER, env=env)
        replacement.start(0)
        assert replacement.van.my_node.id == server_rank_to_id(1)
        assert replacement.is_recovery
        replacement.van.stop()
        # Survivors finalize without the victim: barrier would hang, so stop
        # vans directly (crash-exit path).
        for po in [cluster.scheduler, cluster.workers[0]] + [
            s for s in cluster.servers if s is not victim
        ]:
            po.van.stop()
    except BaseException:
        raise


def test_two_dead_nodes_recovery_honors_preferred_rank():
    """With SEVERAL simultaneous dead nodes of one role, a rejoining node
    carrying a preferred rank (DMLC_RANK -> aux_id) must inherit THAT
    dead id, not an arbitrary one — reference van.cc:187-225 matches the
    recovered node back to its original rank."""
    cluster = LoopbackCluster(
        num_workers=1,
        num_servers=3,
        env_extra={
            "PS_HEARTBEAT_INTERVAL": "1",
            "PS_HEARTBEAT_TIMEOUT": "2",
        },
    )
    cluster.start()
    victims = []
    replacements = []
    try:
        victims = [
            po for po in cluster.servers
            if po.van.my_node.id in (server_rank_to_id(0),
                                     server_rank_to_id(2))
        ]
        for v in victims:
            v.van.stop()
        time.sleep(3.5)
        dead = cluster.scheduler.get_dead_nodes(timeout_s=2)
        assert server_rank_to_id(0) in dead
        assert server_rank_to_id(2) in dead

        # The replacement declares it was rank 2: it must take rank 2's
        # dead id even though rank 0's is also (and "first") available.
        env = Environment(dict(cluster.base_env,
                               DMLC_RANK="2",
                               PS_HEARTBEAT_INTERVAL="1",
                               PS_HEARTBEAT_TIMEOUT="2"))
        replacement = Postoffice(Role.SERVER, env=env)
        replacements.append(replacement)
        replacement.start(0)
        assert replacement.van.my_node.id == server_rank_to_id(2)
        assert replacement.is_recovery

        # A second replacement with no preference falls back to the first
        # remaining dead id (rank 0).
        env2 = Environment(dict(cluster.base_env,
                                PS_HEARTBEAT_INTERVAL="1",
                                PS_HEARTBEAT_TIMEOUT="2"))
        replacement2 = Postoffice(Role.SERVER, env=env2)
        replacements.append(replacement2)
        replacement2.start(0)
        assert replacement2.van.my_node.id == server_rank_to_id(0)
    finally:
        # Best-effort crash-exit teardown (a finalize barrier would hang
        # without the victims): stop every van that is still running.
        for po in replacements + [
            cluster.scheduler, cluster.workers[0]
        ] + [s for s in cluster.servers if s not in victims]:
            try:
                po.van.stop()
            except Exception:
                pass


def test_heartbeat_timeout_implied_by_interval():
    """Enabling PS_HEARTBEAT_INTERVAL implies a PS_HEARTBEAT_TIMEOUT
    (5 intervals) — heartbeating with no one judging the beats is the
    passive posture the detector replaces."""
    po = Postoffice(Role.SCHEDULER, env=Environment({
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "lo", "DMLC_PS_ROOT_PORT": "1",
        "PS_VAN_TYPE": "loopback",
        "PS_HEARTBEAT_INTERVAL": "2",
    }))
    assert po.van.heartbeat_timeout_s() == 10.0
    # An explicit timeout wins over the implied default.
    po2 = Postoffice(Role.SCHEDULER, env=Environment({
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "lo", "DMLC_PS_ROOT_PORT": "1",
        "PS_VAN_TYPE": "loopback",
        "PS_HEARTBEAT_INTERVAL": "2", "PS_HEARTBEAT_TIMEOUT": "3",
    }))
    assert po2.van.heartbeat_timeout_s() == 3.0
    # An EXPLICIT 0 opts out of detection (monitoring-only heartbeats).
    po3 = Postoffice(Role.SCHEDULER, env=Environment({
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "lo", "DMLC_PS_ROOT_PORT": "1",
        "PS_VAN_TYPE": "loopback",
        "PS_HEARTBEAT_INTERVAL": "2", "PS_HEARTBEAT_TIMEOUT": "0",
    }))
    assert po3.van.heartbeat_timeout_s() == 0.0


def test_registration_seeds_heartbeat_entries():
    """Heartbeat entries are seeded at registration time on BOTH sides:
    the scheduler seeds every registrant (pre-existing) and every
    non-scheduler seeds the scheduler on roster receipt — so a
    late-registering node cannot be aged from process start and
    declared dead before its first heartbeat window."""
    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    try:
        # No PS_HEARTBEAT_INTERVAL: the only entries are the seeds.
        assert set(cluster.scheduler._heartbeats) >= {8, 9}
        for po in cluster.servers + cluster.workers:
            assert SCHEDULER_ID in po._heartbeats
            assert po.get_dead_nodes(timeout_s=30) == []
    finally:
        cluster.finalize()


def test_failure_detector_broadcast_marks_peers_down():
    """The scheduler's scan thread notices a silent server and
    broadcasts NODE_FAILURE: surviving peers mark it down, run the
    postoffice hook registry, and fail sends to it fast."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=2,
        env_extra={
            "PS_HEARTBEAT_INTERVAL": "0.3",
            "PS_HEARTBEAT_TIMEOUT": "1.0",
        },
    )
    cluster.start()
    worker_po = cluster.workers[0]
    events = []
    worker_po.register_node_failure_hook(
        lambda nid, down: events.append((nid, down))
    )
    victim = next(
        po for po in cluster.servers
        if po.van.my_node.id == server_rank_to_id(1)
    )
    try:
        victim.van.stop()  # crash: heartbeats cease
        deadline = time.monotonic() + 15
        dead_id = server_rank_to_id(1)
        while ((dead_id, True) not in events
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert (dead_id, True) in events
        assert worker_po.van.is_peer_down(dead_id)
        # Survivors are NOT down.
        assert not worker_po.van.is_peer_down(server_rank_to_id(0))
    finally:
        for po in [cluster.scheduler, cluster.workers[0]] + [
            s for s in cluster.servers if s is not victim
        ]:
            po.van.stop()


def test_wait_raises_timeout_against_killed_server():
    """A push to a dead server must surface TimeoutError through the
    existing wait(ts) path instead of hanging forever."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={
            "PS_HEARTBEAT_INTERVAL": "0.3",
            "PS_HEARTBEAT_TIMEOUT": "1.0",
            "PS_REQUEST_TIMEOUT": "0.3",
            "PS_REQUEST_RETRIES": "2",
        },
    )
    cluster.start()
    srv = KVServer(0, postoffice=cluster.servers[0])
    srv.set_request_handle(KVServerDefaultHandle())
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.array([3], dtype=np.uint64)
    vals = np.ones(8, dtype=np.float32)
    try:
        worker.wait(worker.push(keys, vals))  # healthy round first
        cluster.servers[0].van.stop()  # crash
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            worker.wait(worker.push(keys, vals))
        # Bounded: timeout*2^1 + timeout*2^2 + slack, nowhere near a hang.
        assert time.monotonic() - t0 < 10.0
        # Callbacks for abandoned requests are suppressed.
        fired = []
        with pytest.raises(TimeoutError):
            worker.wait(worker.push(keys, vals,
                                    callback=lambda: fired.append(1)))
        assert not fired
    finally:
        worker.stop()
        srv.stop()
        for po in [cluster.scheduler, cluster.workers[0]]:
            po.van.stop()


def test_resender_exhaustion_fails_owning_request():
    """When the resender's retry budget runs out, the owning request is
    failed (synthesized OPT_SEND_FAILED response -> TimeoutError) — the
    old behavior was log.warning + silent delete, leaving the caller
    hanging forever."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={"PS_RESEND": "1", "PS_RESEND_TIMEOUT": "40"},
    )
    cluster.start()
    srv = KVServer(0, postoffice=cluster.servers[0])
    srv.set_request_handle(KVServerDefaultHandle())
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.array([3], dtype=np.uint64)
    vals = np.ones(8, dtype=np.float32)
    try:
        worker.wait(worker.push(keys, vals))
        cluster.servers[0].van.stop()  # endpoint gone: sends now fail
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            # 10 retries x 40ms ~= 0.4s, then the give-up fails the ts.
            worker.wait(worker.push(keys, vals))
        assert time.monotonic() - t0 < 30.0
    finally:
        worker.stop()
        srv.stop()
        for po in [cluster.scheduler, cluster.workers[0]]:
            po.van.stop()


def test_resender_ack_cache_bounded():
    """The receive-side dedup signature set is bounded FIFO
    (PS_RESEND_ACK_CACHE) — it used to grow without limit forever."""
    class _FakeVan:
        env = Environment({"PS_RESEND_ACK_CACHE": "1024"})

        @staticmethod
        def send(msg):
            pass

        @staticmethod
        def is_peer_down(node_id):
            return False

    r = Resender(_FakeVan(), timeout_ms=10_000)
    try:
        from pslite_tpu.message import Message

        for i in range(3000):
            msg = Message()
            msg.meta.sender = 9
            msg.meta.recver = 8
            msg.meta.timestamp = i
            assert not r.add_incoming(msg)  # first sighting: not a dup
        assert len(r._acked) == 1024
        # Recent signatures still dedup.
        dup = Message()
        dup.meta.sender = 9
        dup.meta.recver = 8
        dup.meta.timestamp = 2999
        assert r.add_incoming(dup)
    finally:
        r.stop()


def test_false_positive_rehabilitation_reaches_peers():
    """A peer falsely declared dead (slow, not crashed) is
    rehabilitated on its next heartbeat — on the scheduler AND on every
    peer that received the NODE_FAILURE broadcast (they have no other
    way to learn the node is back)."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={"PS_HEARTBEAT_INTERVAL": "0.2",
                   "PS_HEARTBEAT_TIMEOUT": "30"},
    )
    cluster.start()
    victim_id = server_rank_to_id(0)
    sched_van = cluster.scheduler.van
    worker_van = cluster.workers[0].van
    try:
        # Simulate a past false declaration: scheduler announced it,
        # the worker heard the broadcast and marked the peer down.
        sched_van._announced_dead.add(victim_id)
        sched_van.mark_peer_down(victim_id)
        worker_van.mark_peer_down(victim_id)
        # The (alive) server's next heartbeat rehabilitates everywhere.
        deadline = time.monotonic() + 10
        while (worker_van.is_peer_down(victim_id)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert not sched_van.is_peer_down(victim_id)
        assert not worker_van.is_peer_down(victim_id)
        assert victim_id not in sched_van._announced_dead
    finally:
        cluster.finalize()
