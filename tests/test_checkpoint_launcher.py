"""Checkpoint/resume of server state, and the local launcher (keepalive)."""

import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pslite_tpu.checkpoint import (
    load_kv_store,
    load_train_state,
    restore_engine,
    save_engine,
    save_kv_store,
    save_train_state,
)
from pslite_tpu.parallel import CollectiveEngine, default_mesh
from pslite_tpu.parallel.sparse import SparseEngine


def test_engine_checkpoint_roundtrip(tmp_path):
    mesh = default_mesh()
    eng = CollectiveEngine(mesh=mesh)
    sp = SparseEngine(mesh)
    keys = np.arange(3, dtype=np.uint64)
    eng.register_dense("d", keys, 16)
    eng.push("d", np.ones(48, np.float32))
    sp.register_sparse("t", 20, 4)
    sp.push("t", np.zeros((8, 2), np.int32), np.ones((8, 2, 4), np.float32))

    path = str(tmp_path / "ckpt")
    save_engine(eng, path, sparse_engine=sp)

    eng2 = CollectiveEngine(mesh=mesh)
    sp2 = SparseEngine(mesh)
    eng2.register_dense("d", keys, 16)
    sp2.register_sparse("t", 20, 4)
    restore_engine(eng2, path, sparse_engine=sp2)

    np.testing.assert_allclose(
        np.asarray(eng2.pull("d")), np.asarray(eng.pull("d"))
    )
    idx = np.zeros((8, 2), np.int32)
    np.testing.assert_allclose(
        np.asarray(sp2.pull("t", idx)), np.asarray(sp.pull("t", idx))
    )


def test_kv_store_roundtrip(tmp_path):
    store = {5: np.arange(4, dtype=np.float32), 9: np.ones(2, np.float32)}
    path = str(tmp_path / "kv")
    save_kv_store(store, path)
    out = load_kv_store(path)
    assert set(out) == {5, 9}
    np.testing.assert_array_equal(out[5], store[5])


def test_train_state_roundtrip(tmp_path):
    import jax.numpy as jnp

    store = jnp.arange(10, dtype=jnp.float32)
    path = str(tmp_path / "train")
    save_train_state(store, 42, path)
    restored, step = load_train_state(path)
    assert step == 42
    np.testing.assert_array_equal(restored, np.arange(10, dtype=np.float32))


def test_local_launcher_runs_cluster(tmp_path):
    """Launch a real 1s+2w cluster through the tracker CLI."""
    child = os.path.join(os.path.dirname(__file__), "tcp_child.py")
    env = dict(os.environ, DMLC_NUM_WORKER="2", DMLC_NUM_SERVER="2")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pslite_tpu.tracker.local",
            "-n", "2", "-s", "2", "--", sys.executable, child,
        ],
        capture_output=True,
        timeout=180,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(child))),
    )
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]


def test_local_launcher_keepalive_restart(tmp_path):
    """A child exiting 254 must be restarted (elastic keepalive)."""
    marker = tmp_path / "restarted"
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        "m = sys.argv[1]\n"
        "if os.environ['DMLC_ROLE'] == 'scheduler':\n"
        "    if not os.path.exists(m):\n"
        "        open(m, 'w').close()\n"
        "        sys.exit(254)\n"
        "    print('RESTARTED_OK')\n"
        "sys.exit(0)\n"
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "pslite_tpu.tracker.local",
            "-n", "0", "-s", "0", "--", sys.executable, str(script),
            str(marker),
        ],
        capture_output=True,
        timeout=120,
        cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert b"RESTARTED_OK" in proc.stdout
    assert b"restarting scheduler" in proc.stderr

def test_engine_checkpoint_orbax_roundtrip(tmp_path):
    from pslite_tpu.checkpoint import (
        have_orbax,
        restore_engine_orbax,
        save_engine_orbax,
    )

    if not have_orbax():
        pytest.skip("orbax not installed")
    mesh = default_mesh()
    eng = CollectiveEngine(mesh=mesh)
    sp = SparseEngine(mesh)
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("od", keys, 16)
    eng.push("od", np.full(32, 2.0, np.float32))
    sp.register_sparse("ot", 16, 4)
    sp.push("ot", np.ones((8, 2), np.int32),
            np.ones((8, 2, 4), np.float32))

    path = str(tmp_path / "orbax_ckpt")
    save_engine_orbax(eng, path, sparse_engine=sp)

    eng2 = CollectiveEngine(mesh=mesh)
    sp2 = SparseEngine(mesh)
    eng2.register_dense("od", keys, 16)
    sp2.register_sparse("ot", 16, 4)
    restore_engine_orbax(eng2, path, sparse_engine=sp2)
    np.testing.assert_allclose(
        np.asarray(eng2.pull("od")), np.asarray(eng.pull("od"))
    )
    idx = np.ones((8, 2), np.int32)
    np.testing.assert_allclose(
        np.asarray(sp2.pull("ot", idx)), np.asarray(sp.pull("ot", idx))
    )


def test_engine_checkpoint_orbax_adagrad_acc(tmp_path):
    """Orbax roundtrip carries the sparse Adagrad accumulator with no
    ensure_acc pre-call by the restorer."""
    from pslite_tpu.checkpoint import (
        have_orbax,
        restore_engine_orbax,
        save_engine_orbax,
    )

    if not have_orbax():
        import pytest

        pytest.skip("orbax not installed")
    import jax
    from jax.sharding import Mesh

    from pslite_tpu.parallel.engine import CollectiveEngine
    from pslite_tpu.parallel.sparse import SparseEngine

    mesh = Mesh(np.array(jax.devices()[:4]), ("kv",))
    rng = np.random.default_rng(2)
    rows, dim = 11, 4
    idx = rng.integers(0, rows, size=(4, 3)).astype(np.int32)
    g = rng.normal(size=(4, 3, dim)).astype(np.float32)

    eng = CollectiveEngine(mesh=mesh)
    se = SparseEngine(mesh)
    se.register_sparse("t", rows, dim)
    se.push("t", idx, g, handle="row_adagrad:0.1")
    want_acc = np.asarray(se.acc_array("t"))
    assert (want_acc > 0).any()
    save_engine_orbax(eng, str(tmp_path / "ck"), sparse_engine=se)

    se2 = SparseEngine(mesh)
    se2.register_sparse("t", rows, dim)
    restore_engine_orbax(CollectiveEngine(mesh=mesh), str(tmp_path / "ck"),
                         sparse_engine=se2)
    np.testing.assert_allclose(np.asarray(se2.acc_array("t")), want_acc)


def test_engine_checkpoint_orbax_cross_fleet(tmp_path):
    """The r04 verdict's weak #7: orbax checkpoints must be fleet-size
    portable like npz v2 — save on an 8-shard engine, restore into a
    4-shard one (dense + adam state + sparse table + adagrad acc), and
    vice versa."""
    from pslite_tpu.checkpoint import (
        have_orbax,
        restore_engine_orbax,
        save_engine_orbax,
    )

    if not have_orbax():
        pytest.skip("orbax not installed")
    import jax
    from jax.sharding import Mesh

    rng = np.random.default_rng(5)
    keys = np.arange(3, dtype=np.uint64)
    val_len = 7  # odd: total_len 21 pads differently at 8 vs 4 shards
    rows, dim = 11, 4
    base_idx = rng.integers(0, rows, size=6).astype(np.int32)
    g_dense = rng.normal(size=(21,)).astype(np.float32)
    g_row = rng.normal(size=(6, dim)).astype(np.float32)

    def build(n_dev):
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("kv",))
        eng = CollectiveEngine(mesh=mesh)
        se = SparseEngine(mesh)
        eng.register_dense("d", keys, val_len)
        se.register_sparse("t", rows, dim)
        return eng, se

    for n_save, n_restore in ((8, 4), (4, 8)):
        eng, se = build(n_save)
        # Adam state: exercises vector slots + the step scalar.  Sparse
        # idx/grads carry one row per worker (every worker pushes the
        # same rows — the aggregate is W x g_row, fleet-dependent, but
        # save vs restore comparisons stay within one fleet's push).
        idx = np.tile(base_idx, (n_save, 1))
        g_sparse = np.tile(g_row, (n_save, 1, 1))
        eng.push_pull("d", g_dense, handle="adam:0.01")
        se.push("t", idx, g_sparse, handle="row_adagrad:0.1")
        want_dense = np.asarray(eng.pull("d"))
        want_tbl = np.asarray(se.pull("t", idx))[0]  # [W,6,d] -> worker 0
        want_kind, want_opt = eng.opt_state("d")
        path = str(tmp_path / f"xf_{n_save}_{n_restore}")
        save_engine_orbax(eng, path, sparse_engine=se)

        eng2, se2 = build(n_restore)
        restore_engine_orbax(eng2, path, sparse_engine=se2)
        np.testing.assert_allclose(
            np.asarray(eng2.pull("d")), want_dense, rtol=1e-6)
        idx2 = np.tile(base_idx, (n_restore, 1))
        np.testing.assert_allclose(
            np.asarray(se2.pull("t", idx2))[0], want_tbl, rtol=1e-6)
        got_kind, got_opt = eng2.opt_state("d")
        assert got_kind == want_kind == "adam"
        for i, (w, g) in enumerate(zip(want_opt, got_opt)):
            w, g = np.asarray(w), np.asarray(g)
            if i == 2:  # step counter: per-shard broadcast, compare value
                np.testing.assert_allclose(g.reshape(-1)[0],
                                           w.reshape(-1)[0])
            else:  # vector slots: compare the logical prefix
                np.testing.assert_allclose(g[:21], w[:21], rtol=1e-6)


def test_orbax_legacy_layout_restore(tmp_path):
    """Regression: a hand-built LEGACY-layout orbax checkpoint (raw
    physical store arrays, no format_v2 marker — what pre-v2 code
    wrote) must still restore through restore_engine_orbax's legacy
    path, same-fleet."""
    from pslite_tpu.checkpoint import have_orbax, restore_engine_orbax

    if not have_orbax():
        pytest.skip("orbax not installed")
    import orbax.checkpoint as ocp

    mesh = default_mesh()
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("ld", keys, 10)  # total 20: pads on 8 shards
    eng.push("ld", np.arange(20, dtype=np.float32))
    # The legacy layout saved stores PHYSICALLY (padded, this fleet's
    # sharded shape) with NO format marker and NO opt/ subtree.
    legacy_state = {
        "dense": {"ld": np.asarray(eng.store_array("ld"))},
        "sparse": {},
        "sparse_acc": {},
    }
    path = str(tmp_path / "legacy_ckpt")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), legacy_state, force=True)
        ckptr.wait_until_finished()

    eng2 = CollectiveEngine(mesh=mesh)
    eng2.register_dense("ld", keys, 10)
    restore_engine_orbax(eng2, path)
    np.testing.assert_allclose(
        np.asarray(eng2.pull("ld")), np.asarray(eng.pull("ld"))
    )


def test_orbax_probe_failure_warns_and_takes_legacy_path(
        tmp_path, monkeypatch):
    """When the v2 metadata probe fails outright, the restore must say
    'could not determine checkpoint format' BEFORE falling into the
    legacy path (a v2 checkpoint restored blind dies in opaque orbax
    shape errors otherwise)."""
    import logging as pylogging

    from pslite_tpu.checkpoint import have_orbax, restore_engine_orbax

    if not have_orbax():
        pytest.skip("orbax not installed")
    import orbax.checkpoint as ocp

    mesh = default_mesh()
    eng = CollectiveEngine(mesh=mesh)
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("pd", keys, 10)
    eng.push("pd", np.arange(20, dtype=np.float32))
    legacy_state = {
        "dense": {"pd": np.asarray(eng.store_array("pd"))},
        "sparse": {},
        "sparse_acc": {},
    }
    path = str(tmp_path / "probe_ckpt")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), legacy_state, force=True)
        ckptr.wait_until_finished()

    def boom(self, *_a, **_k):
        raise RuntimeError("probe exploded")

    monkeypatch.setattr(ocp.StandardCheckpointer, "metadata", boom)
    eng2 = CollectiveEngine(mesh=mesh)
    eng2.register_dense("pd", keys, 10)
    # The pslite logger doesn't propagate (caplog can't see it): attach
    # a recording handler directly.
    records = []

    class _Capture(pylogging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = pylogging.getLogger("pslite_tpu")
    handler = _Capture(level=pylogging.WARNING)
    logger.addHandler(handler)
    try:
        restore_engine_orbax(eng2, path)
    finally:
        logger.removeHandler(handler)
    assert any("could not determine checkpoint format" in m
               for m in records), records
    np.testing.assert_allclose(
        np.asarray(eng2.pull("pd")), np.asarray(eng.pull("pd"))
    )
