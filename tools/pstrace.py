#!/usr/bin/env python
"""pstrace — live tail-trace explorer (docs/observability.md).

Where psmon answers "what are the rates", pstrace answers "where does
the tail LIVE": it drives the scheduler's ``TRACE_PULL`` broadcast
(``Postoffice.collect_cluster_traces``), which drains every node's
tail-trace span ring, assembles complete request trees by trace id
(``telemetry/trace_store.py``), and attributes each request's wall
time across the pipeline stages (``telemetry/critical_path.py``):

    worker queue → lane wait → wire → server intake queue → decode →
    apply-shard wait → apply → response gate → response wire →
    completion

Library use (any live cluster — attach to your scheduler po)::

    from tools import pstrace
    coll = pstrace.collect(scheduler_po)     # TraceCollector
    print(pstrace.format_top(coll))          # per-stage share table
    print(pstrace.format_slowest(coll, 5))   # slowest traces + flight
    print(pstrace.format_path(coll, tid))    # one trace, stage by stage
    pstrace.export_chrome(coll, "out.json")  # Perfetto-ready JSON

CLI: ``python tools/pstrace.py [--top|--slowest N|--path TID|--export
FILE]`` boots a live 2w+2s TCP demo cluster with tail tracing ON and a
chaos receive delay injected on ONE server, runs a mixed push/pull
storm, and renders the assembled tail — the end-to-end proof that the
critical-path attribution pins the injected stage on the slow server.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

# Script use from anywhere: put the repo root ahead of tools/.
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from pslite_tpu.telemetry.critical_path import STAGES  # noqa: E402


def collect(scheduler_po, timeout_s: float = 5.0):
    """One TRACE_PULL round: drains every node's span ring into the
    scheduler's TraceCollector and returns it (traces accumulate
    across calls; rootless partials retire on the TTL)."""
    return scheduler_po.collect_cluster_traces(timeout_s=timeout_s)


def _ms(us: float) -> str:
    return f"{us / 1000.0:.3f}"


def format_top(coll, slow_frac: float = 0.25) -> str:
    """The "where does the tail live" table: per-stage wall-time
    shares over every assembled trace, and over the SLOWEST
    ``slow_frac`` of them (the population a p99 panel shows)."""
    agg = coll.aggregate(slow_frac=slow_frac)
    if not agg["count"]:
        return ("pstrace: no assembled traces (is PS_TRACE_TAIL set, "
                "and has any request been kept since the last pull?)")
    lines = [
        f"pstrace --top  assembled={agg['count']} "
        f"wall_p50={_ms(agg['wall_p50_us'])}ms "
        f"wall_max={_ms(agg['wall_max_us'])}ms "
        f"(slow set = slowest {agg['slow_count']})",
        f"{'stage':>14} {'all ms':>10} {'all %':>7} "
        f"{'slow ms':>10} {'slow %':>7}",
        "-" * 53,
    ]
    for name in STAGES:
        a = agg["stages"].get(name, {"total_us": 0.0, "share": 0.0})
        s = agg["slow"].get(name, {"total_us": 0.0, "share": 0.0})
        lines.append(
            f"{name:>14} {_ms(a['total_us']):>10} "
            f"{a['share'] * 100:>6.1f}% {_ms(s['total_us']):>10} "
            f"{s['share'] * 100:>6.1f}%"
        )
    lines.append("")
    lines.append(f"tail lives in: {agg['top_stage']} "
                 f"({agg['slow'][agg['top_stage']]['share'] * 100:.1f}% "
                 f"of the slow set's wall)")
    lost = getattr(coll, "lost_spans", 0)
    if lost:
        lines.append(
            f"WARNING: node rings overwrote {lost} span(s) before they "
            f"could be pulled — pull more often or raise PS_TRACE_RING"
        )
    return "\n".join(lines)


def _flight_lines(flight: List[dict], indent: str = "      ") -> List[str]:
    out = []
    for ev in flight:
        extra = {k: v for k, v in ev.items()
                 if k not in ("ts_us", "kind", "severity", "trace")}
        out.append(
            f"{indent}flight [{ev.get('severity', '?').upper()}] "
            f"{ev.get('kind')}: "
            + ", ".join(f"{k}={v}" for k, v in sorted(extra.items()))
        )
    return out


def format_slowest(coll, n: int = 5) -> str:
    """The slowest assembled traces, each with its critical-path
    breakdown, keep reason, critical server, and any flight-recorder
    events correlated by trace id (sheds, failovers, give-ups)."""
    rows = sorted(coll.breakdowns(), key=lambda b: -b["wall_us"])[:n]
    if not rows:
        return "pstrace: no assembled traces"
    lines = [f"pstrace --slowest {n}"]
    for b in rows:
        top3 = sorted(b["stages"].items(), key=lambda kv: -kv[1])[:3]
        wall = max(b["wall_us"], 1e-9)
        stages = "  ".join(
            f"{name}={_ms(us)}ms({us / wall * 100:.0f}%)"
            for name, us in top3 if us > 0
        )
        lines.append(
            f"  {b['trace']}: wall={_ms(b['wall_us'])}ms "
            f"keep={b['keep']}"
            + (f" outcome={b['outcome']}" if b.get("outcome") else "")
            + f" server={b['server']}  {stages}"
        )
        lines.extend(_flight_lines(b.get("flight") or []))
    return "\n".join(lines)


def format_path(coll, tid: str) -> str:
    """One trace end to end: the per-stage serial breakdown (sums to
    the request's wall by construction) and every span on the shared
    timeline."""
    tr = coll.get(tid)
    if tr is None:
        return f"pstrace: unknown trace {tid!r}"
    b = tr.breakdown()
    if b is None:
        return (f"pstrace: trace {tid} has no worker root yet "
                f"(partial — {len(tr.spans)} span(s) collected)")
    wall = max(b["wall_us"], 1e-9)
    lines = [
        f"pstrace --path {tid}  wall={_ms(b['wall_us'])}ms "
        f"keep={b['keep']} worker={b['worker']} server={b['server']}",
        f"{'stage':>14} {'ms':>10} {'%':>6}",
        "-" * 33,
    ]
    for name in STAGES:
        us = b["stages"][name]
        lines.append(f"{name:>14} {_ms(us):>10} {us / wall * 100:>5.1f}%")
    lines.extend(_flight_lines(b.get("flight") or [], indent="  "))
    lines.append("")
    lines.append("spans (t_rel ms, dur ms, node, name):")
    t0 = b["t0_us"]
    for ev in sorted(tr.spans, key=lambda e: e.get("ts", 0.0)):
        lines.append(
            f"  {_ms(ev.get('ts', 0.0) - t0):>9} "
            f"{_ms(ev.get('dur', 0.0)):>9} "
            f"{ev.get('pid', '?'):>4} {ev.get('name')}"
        )
    return "\n".join(lines)


def export_chrome(coll, path: str, tid: Optional[str] = None) -> str:
    """Write assembled traces (or ONE trace with ``tid``) as Chrome
    trace-event JSON — drop the file into Perfetto; every node is its
    own process on the shared timeline."""
    if tid is not None:
        tr = coll.get(tid)
        if tr is None:
            raise KeyError(f"unknown trace {tid!r}")
        doc = tr.chrome()
    else:
        events: List[dict] = []
        roles = {}
        for tr in coll.assembled():
            roles.update(tr.roles)
            events.extend(tr.spans)
        doc = {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"{roles[pid]} {pid}"}}
            for pid in sorted(roles)
        ] + sorted(events, key=lambda e: e.get("ts", 0.0)),
            "displayTimeUnit": "ms"}
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


# -- CLI demo ----------------------------------------------------------------


def _demo_cluster(slow_server_delay_ms=(5, 15)):
    """Boot a live 2w+2s cluster over REAL TCP sockets with tail
    tracing on and a chaos receive delay wrapped around server 1 —
    the injected tail the demo's attribution must pin."""
    import threading

    from pslite_tpu.environment import Environment
    from pslite_tpu.message import Role
    from pslite_tpu.postoffice import Postoffice
    from pslite_tpu.utils.network import get_available_port

    host, port = "127.0.0.1", get_available_port()
    base = {
        "DMLC_NUM_WORKER": "2",
        "DMLC_NUM_SERVER": "2",
        "DMLC_PS_ROOT_URI": host,
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NODE_HOST": host,
        "PS_VAN_TYPE": "tcp",
        "PS_TRACE_TAIL": "slow:p90,errors,floor:0.05",
    }
    lo, hi = slow_server_delay_ms
    slow = dict(base, PS_VAN_TYPE="chaos+tcp",
                PS_CHAOS=f"seed=11,delay={lo}:{hi}")
    nodes = [Postoffice(Role.SCHEDULER, env=Environment(dict(base)))]
    nodes.append(Postoffice(Role.SERVER, env=Environment(dict(base))))
    nodes.append(Postoffice(Role.SERVER, env=Environment(slow)))
    nodes += [Postoffice(Role.WORKER, env=Environment(dict(base)))
              for _ in range(2)]
    threads = [threading.Thread(target=po.start, args=(0,), daemon=True)
               for po in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return nodes


def _demo(args) -> int:
    import numpy as np

    from pslite_tpu.benchmark import _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker)

    nodes = _demo_cluster()
    scheduler, server_pos, worker_pos = nodes[0], nodes[1:3], nodes[3:]
    servers, workers = [], []
    try:
        for po in server_pos:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        workers = [KVWorker(0, 0, postoffice=po) for po in worker_pos]
        # Mixed storm spanning BOTH servers' key ranges: the chaos
        # delay on server 1 should surface as wire-stage tail there.
        keys = np.array([3, 2 ** 62, 2 ** 63 + 9, 2 ** 63 + 2 ** 62],
                        dtype=np.uint64)
        vals = np.ones(len(keys) * 256, dtype=np.float32)
        out = np.zeros_like(vals)
        for i in range(args.rounds):
            tss = [w.push(keys, vals) for w in workers]
            for w, ts in zip(workers, tss):
                w.wait(ts)
            if i % 4 == 3:
                workers[0].wait(workers[0].pull(keys, out))
        coll = collect(scheduler, timeout_s=10.0)
        if args.export:
            path = export_chrome(coll, args.export, tid=args.path)
            print(f"pstrace: wrote {path}")
        elif args.path:
            print(format_path(coll, args.path))
        elif args.slowest:
            print(format_slowest(coll, args.slowest))
        else:
            print(format_top(coll))
            print()
            print(format_slowest(coll, 3))
    finally:
        _teardown_cluster(nodes, workers, servers)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--top", action="store_true",
                    help="per-stage critical-path share table (default)")
    ap.add_argument("--slowest", type=int, metavar="N", default=0,
                    help="show the N slowest assembled traces with "
                         "correlated flight events")
    ap.add_argument("--path", type=str, metavar="TRACE", default=None,
                    help="full stage-by-stage breakdown of one trace id")
    ap.add_argument("--export", type=str, metavar="FILE", default=None,
                    help="write assembled traces (or --path's trace) "
                         "as Chrome/Perfetto trace JSON")
    ap.add_argument("--rounds", type=int, default=48,
                    help="demo storm rounds before collecting")
    args = ap.parse_args(argv)
    return _demo(args)


if __name__ == "__main__":
    sys.exit(main())
