"""Van transport family.

Equivalent of the reference's pluggable Van layer (``src/van.cc:43-104``
factory): ``tcp`` (zmq-van analog, DCN/control-plane workhorse), ``loopback``
(in-process fake for unit tests — the tier the reference fork dropped),
``ici`` (flagship TPU data plane over XLA collectives), ``shm`` (same-host
IPC fast path), ``multi`` (multi-rail composite).
"""

from __future__ import annotations

from typing import Optional


def transport_class(van_type: str) -> Optional[type]:
    """Resolve a van type name to its class — THE name→class table,
    shared by :func:`create` and the chaos wrapper (two private copies
    would drift).  None for unknown names."""
    if van_type in ("tcp", "zmq", "0", ""):
        from .tcp_van import TcpVan

        return TcpVan
    if van_type == "loopback":
        from .loopback_van import LoopbackVan

        return LoopbackVan
    if van_type == "ici":
        from .ici_van import IciVan

        return IciVan
    if van_type in ("ici_tcp", "ici+tcp", "xla"):
        from .ici_van import IciTcpVan

        return IciTcpVan
    if van_type in ("ici_shm", "ici+shm"):
        from .ici_van import IciShmVan

        return IciShmVan
    if van_type == "shm":
        from .shm_van import ShmVan

        return ShmVan
    if van_type in ("multi", "multivan"):
        from .multi_van import MultiVan

        return MultiVan
    return None


def create(van_type: str, postoffice):
    try:
        cls = transport_class(van_type)
        if cls is not None:
            return cls(postoffice)
        if van_type == "chaos" or van_type.startswith("chaos+"):
            # Chaos-injection wrapper (docs/fault_tolerance.md): wraps
            # any socket/loopback transport with the seeded PS_CHAOS
            # fault injector.  "chaos" alone wraps PS_CHAOS_INNER
            # (default tcp); "chaos+shm" etc. name the inner explicitly.
            from .chaos_van import create_chaos

            inner = (
                van_type.split("+", 1)[1] if "+" in van_type
                else (postoffice.env.find("PS_CHAOS_INNER") or "tcp")
            )
            return create_chaos(inner, postoffice)
    except ImportError as exc:
        raise ValueError(
            f"van type {van_type!r} is not available in this build: {exc}"
        ) from exc
    raise ValueError(f"unknown van type: {van_type!r}")
