"""Chain replication of accepted pushes across servers
(``PS_KV_REPLICATION=k``, docs/fault_tolerance.md).

With replication enabled, every server forwards each accepted worker
push to the next ``k-1`` servers in rank order (the chain wraps), so a
server's key range survives its death:

- **Forwarding** happens on the server's single request-processing
  thread, in arrival order, into ONE send lane per replica — so a
  replica applies the primary's stream in exactly the primary's arrival
  order.  Combined with the apply pool's shard affinity (per-key apply
  order == arrival order, docs/apply_shards.md) the replica's store is
  **bit-exact** with the primary's.
- **Failover**: on a ``NODE_FAILURE`` broadcast, workers re-route the
  dead rank's key range to its first live replica
  (``KVWorker``'s node-failure hook), which already holds the data.
- **Dedup**: a forwarded push carries ``OPT_REPLICA`` with the ORIGIN
  worker id in ``meta.addr`` and the origin timestamp, so a worker's
  failover retry of a request the primary already forwarded applies
  exactly once (the retry and the forwarded copy share an origin
  identity).
- **Recovery restore**: a recovered server fetches its range's state
  from its first replica (``REPLICA_FETCH_CMD``) before serving —
  replacing the old silent-empty-store rejoin.

Replicas never re-forward (``OPT_REPLICA`` stops the chain) and never
emit app-level responses for forwarded pushes (``KVServer.response``
suppresses them); delivery reliability rides the van-level resender
when ``PS_RESEND`` is on.  Restore moves the handle's ``store`` (or the
pair ``export_range``/``import_range`` when the handle defines them);
``KVServerOptimizerHandle`` packs its momentum/adam slots into that
same iterator currency (docs/durability.md), so replica restores,
elastic range migrations, and cluster snapshots all carry optimizer
state — it no longer restarts fresh or strands on the old owner.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..message import Message, OPT_REPLICA
from ..sarray import SArray
from ..utils import logging as log
from ..utils.bounded import BoundedKeySet

# meta.head (cmd) marking a replica state-fetch: the request's two keys
# are [range_begin, range_end); the response carries every stored key in
# that range with per-key lens.
REPLICA_FETCH_CMD = 0x5EED


def chain_ranks(group_rank: int, k: int, num_servers: int,
                active: Optional[List[int]] = None) -> List[int]:
    """The replica chain of a server rank: the next ``k-1`` group ranks
    in rank order, wrapping.  THE single source of the chain topology —
    servers use it to pick forward targets and workers to pick failover
    destinations; two private copies would silently diverge.

    ``active`` (docs/elasticity.md) restricts the chain to the LIVE
    ranks of an elastic cluster — chains recompute per routing epoch,
    skipping departed ranks and including joiners; with ``active=None``
    the static ``(rank + i) % num_servers`` order is unchanged."""
    if active is not None:
        order = sorted(set(active) | {group_rank})
        idx = order.index(group_rank)
        rot = order[idx + 1:] + order[:idx]
        k = min(k, len(order))
        return rot[: max(k - 1, 0)]
    k = min(k, max(num_servers, 1))
    return [
        (group_rank + i) % num_servers
        for i in range(1, k)
        if (group_rank + i) % num_servers != group_rank
    ]


def _snapshot_items(store, begin: int, end: int):
    """Snapshot a store's (key, value) pairs for ``[begin, end)``.
    Prefers the store's own range-aware iterator (TieredStore: reads
    only that range's cold bytes instead of materializing the whole
    beyond-RAM table once per owned range); plain dicts fall back to
    the short retry loop (apply-shard threads insert concurrently —
    a bare iteration would raise ``dictionary changed size``)."""
    ranged = getattr(store, "items_in_range", None)
    if callable(ranged):
        return ranged(begin, end)
    items = None
    for _ in range(100):
        try:
            items = list(store.items())
            break
        except RuntimeError:
            continue
    log.check(items is not None, "could not snapshot the store")
    return items


def export_range(handle, begin: int, end: int):
    """Snapshot every stored key of ``handle`` in ``[begin, end)`` as
    ``(keys, flat vals, per-key lens)`` — the currency of both the
    replica state fetch and elastic range migration.  Prefers the
    handle's own ``export_range`` hook; otherwise snapshots ``store``
    with a short retry loop (apply-shard threads insert concurrently —
    a bare iteration would raise ``dictionary changed size``)."""
    if callable(getattr(handle, "export_range", None)):
        return handle.export_range(begin, end)
    store = getattr(handle, "store", None) or {}
    items = _snapshot_items(store, begin, end)
    pairs = sorted((kk, arr) for kk, arr in items if begin <= kk < end)
    keys = np.asarray([kk for kk, _ in pairs], dtype=np.uint64)
    lens = np.asarray([arr.size for _, arr in pairs], dtype=np.int32)
    vals = (
        np.concatenate([arr.reshape(-1) for _, arr in pairs])
        if pairs else np.empty(0, np.float32)
    )
    return keys, vals, lens


def import_range(handle, keys, vals, lens) -> None:
    """Load an exported range into ``handle`` (the inverse of
    :func:`export_range`; prefers the handle's ``import_range``)."""
    if callable(getattr(handle, "import_range", None)):
        handle.import_range(keys, vals, lens)
        return
    store = getattr(handle, "store", None)
    log.check(store is not None,
              "state import needs a handle with .store or import_range()")
    off = 0
    for i, key in enumerate(keys):
        # A negative len tags a slot-packed optimizer record
        # (kv_app.KVServerOptimizerHandle.export_range — magnitude =
        # record length).  A plain dict store cannot unpack it:
        # storing the raw record would silently serve parameters with
        # momentum/adam state appended, so refuse loudly instead
        # (restore an optimizer-written snapshot with an optimizer
        # handle).
        raw = int(lens[i]) if lens is not None else (
            len(vals) // max(len(keys), 1)
        )
        log.check(
            raw >= 0,
            f"key {int(key)}: slot-packed optimizer record cannot "
            f"import into a plain store — use the matching optimizer "
            f"handle",
        )
        store[int(key)] = vals[off:off + raw].copy()
        off += raw


class Replicator:
    """Per-KVServer replication engine: forwarding, origin dedup, and
    the recovery fetch/restore protocol."""

    def __init__(self, server, k: int):
        self._server = server
        self.po = server.po
        self.k = min(k, max(self.po.num_servers, 1))
        # Origin identities already applied on this server, bounded FIFO
        # (the ack-cache pattern): (origin_sender, customer, ts, key).
        self._applied = BoundedKeySet(max(
            1024, self.po.env.find_int("PS_REPLICA_DEDUP_CACHE", 65536)
        ))
        self._mu = threading.Lock()
        self._restore_ts: Optional[int] = None
        self._restore_msg: Optional[Message] = None
        # One range-state fetch in flight at a time: restore() (boot /
        # rehab thread) and replica backfill (routing-update thread)
        # share the _restore_ts/_restore_msg interception slot.
        self._fetch_mu = threading.Lock()
        # Replica-read stamp bookkeeping (docs/serving_reads.md), keyed
        # by PRIMARY node id: the newest forward stamp CLAIMED at intake
        # (pulls answered from this replica advertise it — per-key apply
        # order == arrival order, so a pull intaken after forward S
        # observes S's effect on every shared key) and the newest stamp
        # whose apply COMPLETED (the lag gauge pages on a replica whose
        # apply pool falls behind its intake).
        self._claimed: Dict[int, int] = {}
        self._applied_stamps: Dict[int, int] = {}
        # Backfill floor (satellite of docs/serving_reads.md): after a
        # range import cut at primary stamp F, forwards stamped <= F are
        # already IN the imported state — re-applying them would
        # double-add (+= semantics).
        self._import_floor: Dict[int, int] = {}
        # Observability (docs/observability.md): registry counters
        # (the forwarded/deduped properties are thin read-throughs —
        # PS_TELEMETRY=0 no-ops them like every other metric) plus a
        # replication-lag gauge — forwards still parked in the send
        # lanes toward this primary's replicas, i.e. writes the
        # replicas have not yet even been sent.
        reg = self.po.metrics
        self._c_forwarded = reg.counter("replication.forwards")
        self._c_deduped = reg.counter("replication.dedup_hits")
        self.po.metrics.gauge("replication.lag", fn=self._pending_forwards)
        # Replica-read freshness (docs/serving_reads.md): max over
        # primaries of (claimed - applied) — forwards this replica has
        # accepted but not yet finished applying.
        self.po.metrics.gauge("replication.applied_stamp_lag",
                              fn=self.stamp_lag)
        # A recovered WORKER restarts its timestamp sequence at 0, so
        # its fresh pushes would collide with the dead incarnation's
        # origin identities still in the dedup cache and be silently
        # dropped — purge that sender's entries on recovery.
        self.po.register_node_failure_hook(self._on_node_event)

    @property
    def forwarded(self) -> int:
        return self._c_forwarded.value

    @property
    def deduped(self) -> int:
        return self._c_deduped.value

    def _pending_forwards(self) -> int:
        """Messages queued in the van's send lanes toward this server's
        replicas (sampled by the ``replication.lag`` gauge)."""
        van = self.po.van
        try:
            ids = self.replica_ids()
        except Exception:  # noqa: BLE001 - pre-bootstrap snapshot
            return 0
        return sum(
            len(lane.q) for rid in ids for lane in van._lanes_of(rid)
        )

    def close(self) -> None:
        self.po.unregister_node_failure_hook(self._on_node_event)

    def _on_node_event(self, node_id: int, down: bool) -> None:
        if down:
            return
        with self._mu:
            n = self._applied.discard_where(lambda o: o[0] == node_id)
            # A recovered PRIMARY restarts its push-version counter at
            # 1: stale claimed/applied/floor entries minted by the dead
            # incarnation would let replica reads advertise versions
            # the new counter can never reach (or skip forwards it
            # legitimately re-sends).
            self._claimed.pop(node_id, None)
            self._applied_stamps.pop(node_id, None)
            self._import_floor.pop(node_id, None)
        if n:
            log.vlog(1, f"purged {n} dedup origins for recovered "
                        f"node {node_id}")

    # -- topology ------------------------------------------------------------

    def replica_ids(self) -> List[int]:
        """Instance ids of my next k-1 chain members, rank order."""
        from ..base import server_rank_to_id

        gs = self.po.group_size
        my_rank = self.po.my_rank()
        g, idx = my_rank // gs, my_rank % gs
        return [
            server_rank_to_id(r * gs + idx)
            for r in chain_ranks(g, self.k, self.po.num_servers,
                                 active=self.po.active_server_ranks)
        ]

    # -- origin dedup --------------------------------------------------------

    @staticmethod
    def _origin(meta) -> Tuple:
        origin_sender = meta.addr if meta.option == OPT_REPLICA else meta.sender
        return (origin_sender, meta.customer_id, meta.timestamp, meta.key)

    def should_apply(self, meta) -> bool:
        """Record a push's origin identity; False when this origin was
        already applied here (a worker's failover retry racing the
        primary's forwarded copy, in either order)."""
        origin = self._origin(meta)
        with self._mu:
            if not self._applied.add(origin):
                self._c_deduped.inc()
                return False
        return True

    # -- replica-read stamp currency (docs/serving_reads.md) -----------------

    def note_claimed(self, primary_id: int, stamp: int) -> None:
        """A forward from ``primary_id`` carrying ``stamp`` was intaken
        (request thread, arrival order): pulls intaken after this point
        observe its effect on every shared key, so this replica may
        ADVERTISE the stamp on its pull responses."""
        if stamp <= 0:
            return
        with self._mu:
            if stamp > self._claimed.get(primary_id, 0):
                self._claimed[primary_id] = stamp

    def note_applied(self, primary_id: int, stamp: int) -> None:
        """A forward's apply completed (apply-pool shard thread / serial
        path) — feeds the ``replication.applied_stamp_lag`` gauge."""
        if stamp <= 0:
            return
        with self._mu:
            if stamp > self._applied_stamps.get(primary_id, 0):
                self._applied_stamps[primary_id] = stamp

    def claimed_stamp(self, primary_id: int) -> int:
        """The newest forward stamp this replica has intaken from
        ``primary_id`` (0 before the first stamped forward/backfill)."""
        with self._mu:
            return self._claimed.get(primary_id, 0)

    def stamp_lag(self) -> int:
        """Max over primaries of (claimed - applied): forwards accepted
        at intake whose apply has not yet completed."""
        with self._mu:
            if not self._claimed:
                return 0
            return max(
                c - self._applied_stamps.get(pid, 0)
                for pid, c in self._claimed.items()
            )

    def set_import_floor(self, primary_id: int, stamp: int) -> None:
        """A range import from ``primary_id`` was cut at ``stamp``
        (quiesced export — every forward <= stamp is IN the imported
        state): forwards at or below the floor must ack without
        applying, or += pushes would double-add."""
        if stamp <= 0:
            return
        with self._mu:
            if stamp > self._import_floor.get(primary_id, 0):
                self._import_floor[primary_id] = stamp
            if stamp > self._claimed.get(primary_id, 0):
                self._claimed[primary_id] = stamp
            if stamp > self._applied_stamps.get(primary_id, 0):
                self._applied_stamps[primary_id] = stamp

    def below_import_floor(self, meta) -> bool:
        """True when this forward's effect is already covered by a
        backfill import's cut (see :meth:`set_import_floor`)."""
        stamp = getattr(meta, "stamp", 0)
        if stamp <= 0:
            return False
        with self._mu:
            return stamp <= self._import_floor.get(meta.sender, 0)

    # -- forwarding (primary side) -------------------------------------------

    def forward(self, meta, kvs, copy: bool = False,
                wire=None) -> None:
        """Chain-forward an accepted worker push to the next k-1
        servers.  Runs on the server's single request-processing thread,
        so forwards enter each replica's send lane in arrival order;
        priority is pinned to one level so the lane's FIFO-within-level
        IS the arrival order (bit-exactness depends on it).

        ``copy=True`` snapshots the payload first — required when vals
        alias a registered recv buffer, which the pump overwrites with
        the sender's next push while the replica lane may still be
        serializing this one.

        ``wire`` (docs/compression.md) is a codec push's COMPRESSED
        payload as received: ``(codes, scales, lens|None, CodecInfo)``.
        When present the forward re-sends those exact bytes with the
        EXT_CODEC extension — the replica decodes once on arrival —
        instead of the decoded float32 vals, which paid
        decompress+recompress and ~4x wire on every chain hop.  The
        segments alias the receive frame; the SArray refs keep the
        pooled block alive until the lane serialized them (the same
        lifetime rule as the uncompressed path), and a registered recv
        buffer never backs them, so ``copy`` does not apply.

        Chunking interplay (docs/chunking.md): a large forward is
        RE-CHUNKED by ``van.send`` under the forwarding server's own
        xfer ids, while the ORIGIN identity (meta.addr = origin worker,
        meta.timestamp, meta.key) rides every chunk unchanged — the
        replica reassembles the forward and dedups a worker's failover
        retry of the same push exactly once, whether the retry arrives
        chunked or monolithic.  Streaming apply is disabled on
        replicated servers (``KVServer._stream_eligible``): the forward
        must observe the COMPLETE payload at its arrival-order slot, so
        pushes apply only after full reassembly, exactly like the
        monolithic path."""
        van = self.po.van
        vals = None
        if wire is None:
            vals = kvs.vals.copy() if copy else kvs.vals
        for rid in self.replica_ids():
            if van.is_peer_down(rid):
                continue
            msg = Message()
            m = msg.meta
            m.app_id = self._server._customer.app_id
            m.customer_id = meta.customer_id
            m.request = True
            m.push = True
            m.pull = False
            m.head = meta.cmd
            # Origin identity rides (addr, timestamp, key): the replica
            # dedups a worker's failover retry of this same request.
            m.timestamp = meta.timestamp
            m.addr = meta.sender
            m.key = meta.key
            m.option = OPT_REPLICA
            m.recver = rid
            m.priority = 0
            # Forwards join the origin request's trace: the replica's
            # recv/apply spans land under the same trace id.
            m.trace = getattr(meta, "trace", 0)
            # Carry the originating tenant's EXT_QOS label
            # (docs/qos.md): replica-side per-tenant metrics, weighted
            # apply scheduling, and admission backlogs must account the
            # TRUE tenant, not lump every forward onto tenant 0.
            m.tenant = getattr(meta, "tenant", 0)
            # Replica-read consistency currency (docs/serving_reads.md):
            # the push stamp the primary assigned at intake rides every
            # forward (EXT_QOS), so the replica can advertise exactly
            # how much of the primary's write stream its pull responses
            # cover.  0 when stamping is off — replica reads then have
            # no currency and stay disabled.
            m.stamp = getattr(meta, "stamp", 0)
            msg.add_data(SArray(kvs.keys))
            if wire is not None:
                codes, scales, lens_arr, ci = wire
                m.codec = ci
                m.val_len = ci.raw_len
                msg.add_data(codes if isinstance(codes, SArray)
                             else SArray(codes))
                msg.add_data(scales if isinstance(scales, SArray)
                             else SArray(scales))
                if lens_arr is not None:
                    msg.add_data(
                        SArray(np.asarray(lens_arr, dtype=np.int32))
                    )
            else:
                msg.add_data(SArray(vals))
                if kvs.lens is not None:
                    msg.add_data(
                        SArray(np.asarray(kvs.lens, dtype=np.int32))
                    )
            try:
                van.send(msg)
                self._c_forwarded.inc()
            except Exception as exc:  # noqa: BLE001 - replica may be down
                log.warning(f"replica forward to {rid} failed: {exc!r}")

    # -- state fetch (replica side) ------------------------------------------

    def handle_fetch(self, meta, kvs, server) -> None:
        """Serve a range-state fetch (recovered primary restore, or a
        new chain member's backfill): every stored key in [begin, end),
        with per-key lens.  Runs on the request thread; the apply pool
        is quiesced first so the export is a CLEAN cut — everything
        intaken before this fetch has applied, which makes the fetch
        response's stamp (captured at intake) the exact upper bound of
        the cut, the backfill import floor depends on it."""
        log.check(len(kvs.keys) >= 2, "replica fetch wants [begin, end)")
        begin, end = int(kvs.keys[0]), int(kvs.keys[1])
        handle = server._handle
        from .kv_app import KVPairs

        pool = getattr(server, "_apply_pool", None)
        if pool is not None:
            tok = pool.submit_token()
            if not pool.quiesce(tok, timeout_s=30.0):
                log.warning("replica fetch: apply pool did not quiesce "
                            "in 30s; exporting anyway (stamp may "
                            "over-claim the cut)")
        keys, vals, lens = export_range(handle, begin, end)
        log.vlog(1, f"replica fetch [{begin}, {end}): {len(keys)} keys")
        server.response(meta, KVPairs(keys=keys, vals=vals, lens=lens))

    # -- restore (recovered primary side) ------------------------------------

    def absorb_response(self, msg: Message) -> bool:
        """Intercept the in-flight restore's response (KVServer routes
        every non-request here before discarding it)."""
        if self._restore_ts is None or msg.meta.timestamp != self._restore_ts:
            return False
        self._restore_msg = msg
        return True

    def restore(self, handle, timeout_s: float = 30.0) -> int:
        """Fetch the state of EVERY range this server holds — its own
        key range (from its chain) plus the replica copies it keeps for
        the ranks whose chains include it (from those primaries) — and
        load it into ``handle``.  Run BEFORE serving, replacing the old
        silent-empty-store recovery; restoring only the own range would
        void the durability guarantee for the OTHER primaries' ranges
        the moment this replica rejoined empty.  Returns the number of
        keys restored (0 when nothing is reachable — logged, not fatal:
        an empty rejoin is still better than refusing to rejoin)."""
        from ..base import server_rank_to_id

        gs = self.po.group_size
        my_rank = self.po.my_rank()
        g, idx = my_rank // gs, my_rank % gs
        num = self.po.num_servers
        active = self.po.active_server_ranks
        ranks = active if active is not None else list(range(num))
        to_id = lambda r: server_rank_to_id(r * gs + idx)  # noqa: E731
        chain = lambda r: chain_ranks(r, self.k, num,  # noqa: E731
                                      active=active)
        total = 0
        # My own range(s) — several under elastic routing after a
        # merge: fetch each from my chain members.
        for rng in self.po.server_key_ranges_of(g):
            total += self._fetch_range(
                handle, rng, [to_id(r) for r in chain(g)], timeout_s,
            )[0]
        # Ranges I replicate for others: fetch from the primary first,
        # then its other chain members.
        for r in ranks:
            if r == g or g not in chain(r):
                continue
            for rng in self.po.server_key_ranges_of(r):
                n, stamp, src = self._fetch_range(
                    handle, rng,
                    [to_id(r)] + [
                        to_id(c) for c in chain(r) if c != g
                    ],
                    timeout_s,
                )
                total += n
                if src == to_id(r) and stamp > 0:
                    # Fetched from the PRIMARY itself: the response
                    # stamp is in the primary's currency, so it both
                    # seeds the claimed stamp (replica reads can serve
                    # right away) and floors forward re-applies.
                    self.set_import_floor(src, stamp)
        return total

    def backfill_range(self, handle, rng, primary_id: int,
                       timeout_s: float = 30.0) -> int:
        """Backfill one range this server newly replicates (chain
        recomputation after join/leave/recovery — docs/serving_reads.md)
        from its PRIMARY.  The primary's quiesced export (handle_fetch)
        makes the response stamp the exact cut bound: it becomes the
        import floor, so forwards racing the backfill apply exactly
        once.  Returns the number of keys imported (0 on failure —
        logged, the replica then converges only through new forwards)."""
        n, stamp, src = self._fetch_range(handle, rng, [primary_id],
                                          timeout_s)
        if src == primary_id and stamp > 0:
            self.set_import_floor(primary_id, stamp)
        return n

    def _fetch_range(self, handle, rng, candidate_ids: List[int],
                     timeout_s: float) -> Tuple[int, int, int]:
        """Fetch one key range's state from the first live candidate
        and import it into ``handle``.  Returns ``(keys imported,
        response stamp, source node id)`` — ``(0, 0, -1)`` on failure
        (logged).  Serialized by ``_fetch_mu``: boot restore, rehab
        resync, and replica backfill share one interception slot."""
        van = self.po.van
        rid = next(
            (r for r in candidate_ids if not van.is_peer_down(r)), None
        )
        if rid is None:
            log.warning(f"restore of [{rng.begin}, {rng.end}) skipped: "
                        f"no live holder")
            return 0, 0, -1
        with self._fetch_mu:
            customer = self._server._customer
            ts = customer.new_request(rid)
            self._restore_ts = ts
            self._restore_msg = None
            msg = Message()
            m = msg.meta
            m.app_id = customer.app_id
            m.customer_id = customer.customer_id
            m.request = True
            m.pull = True
            m.head = REPLICA_FETCH_CMD
            m.timestamp = ts
            m.recver = rid
            msg.add_data(SArray(np.asarray([rng.begin, rng.end],
                                           dtype=np.uint64)))
            # Empty vals segment: the server's decode path only
            # populates kvs.keys when the frame carries both segments.
            msg.add_data(SArray(np.empty(0, np.float32)))
            try:
                van.send(msg)
            except Exception as exc:  # noqa: BLE001 - died in the gap
                log.warning(f"restore fetch to {rid} failed: {exc!r}; "
                            f"[{rng.begin}, {rng.end}) left empty")
                self._restore_ts = None
                return 0, 0, -1
            ok = customer.wait_request(ts, timeout=timeout_s)
            resp, self._restore_msg, self._restore_ts = (
                self._restore_msg, None, None
            )
        if not ok or resp is None:
            log.warning(f"restore from {rid} timed out ({timeout_s}s); "
                        f"[{rng.begin}, {rng.end}) left empty")
            return 0, 0, -1
        stamp = getattr(resp.meta, "stamp", 0)
        if len(resp.data) < 2:
            log.vlog(1, f"restore: [{rng.begin}, {rng.end}) is empty")
            return 0, stamp, rid
        keys = resp.data[0].astype_view(np.uint64).numpy()
        vals = resp.data[1].numpy()
        lens = (resp.data[2].astype_view(np.int32).numpy()
                if len(resp.data) > 2 else None)
        import_range(handle, keys, vals, lens)
        log.vlog(1, f"restored {len(keys)} keys of "
                    f"[{rng.begin}, {rng.end}) from node {rid}")
        return len(keys), stamp, rid
