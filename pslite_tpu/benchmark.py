"""KV benchmark CLI — the reference's workhorse benchmark re-created.

Parity with ``tests/test_benchmark.cc``: modes PUSH_THEN_PULL / PUSH_PULL /
PUSH_ONLY / PULL_ONLY (:25-30), ``len repeat mode`` arguments, NUM_KEY_PER_SERVER
keys per server (:407-414), goodput printed every LOG_DURATION rounds with
the same metric definitions (:388-396):

    goodput_gbps = 8 * len * total_key_num * iters / elapsed_ns
    latency_ns_per_key = elapsed / iters / total_key_num / 1000

The server uses an assign-and-echo handle (the reference's EmptyHandler
allocates per-key buffers on first push and echoes them on pull,
:131-203), with val/len consistency checks baked in.  Runs over any van;
launch e.g.::

    python -m pslite_tpu.tracker.local -n 1 -s 1 --van shm -- \
        python -m pslite_tpu.benchmark --len 1024000 --repeat 10 --mode push_pull
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np

MODES = ("push_then_pull", "push_pull", "push_only", "pull_only")


class BenchmarkHandle:
    """Assign on push (allocating on first touch), echo on pull."""

    def __init__(self):
        self.store = {}

    def __call__(self, meta, data, server):
        from .kv.kv_app import KVPairs
        from .utils import logging as log

        if meta.push:
            n = len(data.keys)
            log.check(n > 0 and len(data.vals) % n == 0,
                      "inconsistent val/len in push")
            k = len(data.vals) // n
            for i, key in enumerate(data.keys):
                self.store[int(key)] = np.array(
                    data.vals[i * k : (i + 1) * k]
                )
            server.response(meta)
        else:
            vals = [self.store[int(key)] for key in data.keys]
            server.response(
                meta,
                KVPairs(keys=data.keys, vals=np.concatenate(vals)),
            )


def run_worker(args) -> None:
    from . import postoffice
    from .kv.kv_app import KVWorker
    from .message import Role

    po = postoffice(Role.WORKER)
    worker = KVWorker(0, 0)
    ranges = po.get_server_key_ranges()
    keys_per_server = args.num_keys
    val_len = args.len // 4  # fp32 elements per key
    keys = np.sort(
        np.concatenate(
            [
                np.arange(keys_per_server, dtype=np.uint64) + r.begin
                for r in ranges
            ]
        )
    )
    total_keys = len(keys)
    vals = np.random.default_rng(po.my_rank()).normal(
        size=total_keys * val_len
    ).astype(np.float32)
    outs = np.zeros_like(vals)

    def timed(fn, iters):
        t0 = time.perf_counter_ns()
        for _ in range(iters):
            fn()
        return time.perf_counter_ns() - t0

    def report(tag, elapsed_ns, iters, bytes_per_iter):
        goodput = 8.0 * bytes_per_iter * iters / max(elapsed_ns, 1)
        lat = elapsed_ns / max(iters, 1) / total_keys / 1000.0
        print(
            f"{tag}: {goodput:.3f} Gbps, avg latency {lat:.3f} us/key",
            flush=True,
        )

    # Warm up (registration / first-touch, as the reference's first rounds).
    worker.wait(worker.push(keys, vals))
    worker.wait(worker.pull(keys, outs))

    payload = total_keys * val_len * 4
    log_every = int(os.environ.get("LOG_DURATION", "10"))
    done = 0
    while done < args.repeat:
        iters = min(log_every, args.repeat - done)
        if args.mode == "push_then_pull":
            e1 = timed(lambda: worker.wait(worker.push(keys, vals)), iters)
            report("push", e1, iters, payload)
            e2 = timed(lambda: worker.wait(worker.pull(keys, outs)), iters)
            report("pull", e2, iters, payload)
        elif args.mode == "push_pull":
            e = timed(
                lambda: worker.wait(worker.push_pull(keys, vals, outs)),
                iters,
            )
            report("push_pull", e, iters, 2 * payload)
        elif args.mode == "push_only":
            e = timed(lambda: worker.wait(worker.push(keys, vals)), iters)
            report("push", e, iters, payload)
        else:  # pull_only
            e = timed(lambda: worker.wait(worker.pull(keys, outs)), iters)
            report("pull", e, iters, payload)
        done += iters

    # Correctness: the last pull must echo the last push (assign handle).
    if args.mode in ("push_then_pull", "push_pull"):
        worker.wait(worker.push(keys, vals))
        worker.wait(worker.pull(keys, outs))
        np.testing.assert_allclose(outs, vals, rtol=1e-6)
        print("CHECK_OK", flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--len", type=int, default=1024000,
                    help="bytes per key (default 1024000)")
    ap.add_argument("--repeat", type=int, default=10)
    ap.add_argument("--mode", choices=MODES, default="push_pull")
    ap.add_argument("--num-keys", type=int,
                    default=int(os.environ.get("NUM_KEY_PER_SERVER", "40")))
    args = ap.parse_args(argv)

    from . import KVServer, finalize, start_ps

    role = os.environ["DMLC_ROLE"]
    start_ps()
    server = None
    if role in ("server", "joint"):
        server = KVServer(0)
        server.set_request_handle(BenchmarkHandle())
    if role in ("worker", "joint"):
        run_worker(args)
    finalize()
    if server is not None:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
