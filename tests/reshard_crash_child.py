"""Child for the crash-consistent reshard test: worker rank 1 DIES
before calling reshard; worker rank 0 must time out at the entry
barrier and abort with its engine untouched (old mesh, stores intact).
See vans/ici_van.py reshard_engines CRASH SEMANTICS."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import pslite_tpu as ps  # noqa: E402


def main() -> None:
    role = os.environ["DMLC_ROLE"]
    ps.start_ps()
    if role == "worker":
        rank = int(os.environ["DMLC_RANK"])
        kv = ps.KVWorker(0, 0)
        eng = kv.engine
        keys = np.arange(4, dtype=np.uint64)
        val_len = 8
        kv.register_dense("g", keys, val_len)
        vals = np.full(4 * val_len, float(rank + 1), np.float32)
        outs = np.zeros_like(vals)
        kv.wait(kv.push_pull(keys, vals, outs))
        np.testing.assert_allclose(outs, 12.0)

        mode = os.environ.get("PS_CRASH_MODE", "exit_before")
        if rank == 1 and mode == "exit_before":
            # DIE before the coordinated reshard: no barrier request
            # ever reaches the scheduler from this worker.
            sys.stdout.flush()
            os._exit(42)
        if rank == 1 and mode == "stage_fail":
            # Fail rank 1's STAGING (at the first new-mesh placement —
            # AFTER the collective snapshot legs both ranks run, so the
            # survivor reaches the commit barrier rather than a jax
            # collective): rank 1 must raise fast and go SILENT, never
            # releasing the survivors' commit barrier with a stray
            # resume request.
            from pslite_tpu.parallel import placement

            real = placement.place_host_array

            def fail_first(*a, **kw):
                placement.place_host_array = real
                raise RuntimeError("injected staging failure")

            placement.place_host_array = fail_first

        from jax.sharding import Mesh

        devs = sorted(jax.devices(),
                      key=lambda d: (d.process_index, d.id))
        mesh4 = Mesh(np.array(devs[0:2] + devs[4:6]), ("kv",))
        old_padded = eng.bucket("g").padded_len
        try:
            kv.reshard(mesh4)  # PS_RESHARD_TMO_S set by the parent
            print("CRASH_FAIL reshard succeeded with a dead peer",
                  flush=True)
        except Exception as exc:  # noqa: BLE001 - the expected abort
            ok = (
                eng.num_shards == 8
                and eng.bucket("g").padded_len == old_padded
            )
            # Local shards must still hold the pre-crash state (12.0
            # everywhere) — reads of addressable shards are local.
            for s in eng._stores["g"].addressable_shards:
                ok = ok and np.allclose(np.asarray(s.data), 12.0)
            print(f"CRASH_OK rank={rank} untouched={ok} "
                  f"{type(exc).__name__}", flush=True)
        # Skip finalize: the cluster is degraded by design (dead peer);
        # finalize's ALL_GROUP barrier would wedge.
        sys.stdout.flush()
        os._exit(0)
    ps.finalize()
    print(f"{role} DONE", flush=True)


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # noqa: BLE001 - env-limitation sentinel
        if "Multiprocess computations aren't implemented" not in repr(exc):
            raise
        # This jaxlib's CPU backend cannot run cross-process programs:
        # report the limitation and exit 0 so the parent skips fast
        # (the scheduler/server peers are killed by the parent).
        print("MULTIPROC_UNSUPPORTED", flush=True)
        sys.stdout.flush()
        os._exit(0)
