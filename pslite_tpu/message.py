"""Message model: Node, Control, Meta, Message.

Capability parity with the reference's ``include/ps/internal/message.h``:
``Meta`` carries head/app/customer/timestamp/routing/flags plus the zero-copy
fields (``key``, ``addr``, ``val_len``, ``option``, ``sid``) that let a
transport deliver payloads straight into a pre-registered destination buffer;
``Control`` carries the bootstrap/barrier/heartbeat plane; ``Node`` describes
a process (role, id, address, devices, recovery flag, preferred rank).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .base import EMPTY_ID
from .sarray import DeviceType, SArray


class Role(enum.IntEnum):
    SERVER = 0
    WORKER = 1
    SCHEDULER = 2
    JOINT = 3  # worker + server hosted in one process (reference: ps.h:59-76)


class Command(enum.IntEnum):
    """Control commands (reference: message.h:163-164)."""

    EMPTY = 0
    TERMINATE = 1
    ADD_NODE = 2
    BARRIER = 3
    ACK = 4
    HEARTBEAT = 5
    BOOTSTRAP = 6
    ADDR_REQUEST = 7
    ADDR_RESOLVED = 8
    INSTANCE_BARRIER = 9
    # Active failure detection (docs/fault_tolerance.md): the scheduler's
    # detector thread broadcasts the dead node's identity to surviving
    # peers, which mark it down and fail its parked sends fast.
    NODE_FAILURE = 10
    # Cluster telemetry pull (docs/observability.md): the scheduler asks
    # a node for its metrics-registry snapshot; the reply carries it as
    # JSON in meta.body.  Rides the control plane like BARRIER.
    METRICS_PULL = 11
    # Elastic membership (docs/elasticity.md): the scheduler's versioned
    # routing-table broadcast (RoutingTable JSON in meta.body), and a
    # node's table pull (request=True, stale-epoch self-heal).
    ROUTING = 12
    # Graceful decommission (docs/elasticity.md): a server asks the
    # scheduler to leave the running cluster; the scheduler reassigns
    # its key ranges (ROUTING epoch), the server migrates them, reports
    # completion (REMOVE_DONE_OPT), and the scheduler retires it.
    REMOVE_NODE = 13
    # Tail-trace pull (docs/observability.md): the scheduler drains a
    # node's bounded span ring (the reply carries it as JSON in
    # meta.body, plus trace-correlated flight events); the request body
    # piggybacks windowed-quantile threshold hints for the node's
    # tail-keep policy.  Same broadcast+gather shape as METRICS_PULL.
    TRACE_PULL = 14
    # Coordinated cluster snapshot (docs/durability.md): the scheduler
    # asks every server to fence a consistent cut (apply-pool quiesce)
    # and stream its owned ranges to per-range segment files under the
    # snapshot directory; the reply carries the per-range digests as
    # JSON in meta.body, and the scheduler commits the cut by writing
    # the cluster MANIFEST.  Same broadcast+gather shape as
    # METRICS_PULL.
    SNAPSHOT = 15


# Wire dtype codes (stable across hosts; independent of numpy internals).
_DTYPE_TO_CODE = {
    np.dtype(np.int8): 1,
    np.dtype(np.uint8): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.uint16): 4,
    np.dtype(np.int32): 5,
    np.dtype(np.uint32): 6,
    np.dtype(np.int64): 7,
    np.dtype(np.uint64): 8,
    np.dtype(np.float16): 9,
    np.dtype(np.float32): 10,
    np.dtype(np.float64): 11,
}
_CODE_TO_DTYPE = {v: k for k, v in _DTYPE_TO_CODE.items()}
# bfloat16 rides as code 12 when ml_dtypes is present.
try:  # pragma: no cover - availability depends on environment
    import ml_dtypes

    _DTYPE_TO_CODE[np.dtype(ml_dtypes.bfloat16)] = 12
    _CODE_TO_DTYPE[12] = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    pass


# Zero-copy pull option (is_worker_zpull_): when meta.option == OPT_ZPULL
# on a pull request/response, meta.addr encodes the worker's registered
# pull buffer as (buf_id << ZPULL_OFF_BITS) | slice_byte_offset.  Lives
# here (not the app layer) so transports can consume it without importing
# kv_app.
OPT_ZPULL = 2
ZPULL_OFF_BITS = 40

# meta.option marker: vals travel as int8 blocks + fp32 scales (gradient
# compression for DCN-class links; ops/quantize.py scheme).  Lives here
# for the same layering reason as OPT_ZPULL.
OPT_COMPRESS_INT8 = 1

# meta.option marker on an (empty) response: the server-side handler
# raised while applying this request.  The waiting worker still gets its
# response counted — so ``wait`` unblocks — and ``KVWorker.wait`` raises
# instead of returning silently-unapplied data.  Without this, a handler
# bug left the remote waiter hanging until timeout.
OPT_APPLY_ERROR = 3

# meta.option marker on a LOCALLY synthesized (empty) response: the van
# gave up delivering the request (resender retry budget exhausted, or
# the destination was declared dead with the message still parked in
# its send lane).  The owning ``KVWorker.wait`` raises ``TimeoutError``
# instead of hanging on a message the transport already abandoned.
OPT_SEND_FAILED = 4

# meta.option marker on a server→server forwarded push (chain
# replication, kv/replication.py): the receiver applies the payload but
# never re-forwards it and never emits an app-level response; meta.addr
# carries the ORIGIN worker id and meta.timestamp the origin timestamp
# so a worker's failover retry of the same request dedups exactly once.
OPT_REPLICA = 5

# meta.option marker on a LOCALLY constructed partial delivery of a
# chunked streaming transfer (docs/chunking.md): the van's reassembler
# hands the newly completed whole-key prefix of an in-flight push to
# the app layer so apply overlaps the remaining wire time.  Never on
# the wire (chunks are identified by the ChunkInfo meta extension);
# consumers that can't stream simply drop these — the final complete
# message always follows.
OPT_XFER_PART = 6

# meta.option marker on an (empty) response: the server SHED this
# request under admission control (docs/qos.md — the tenant's bounded
# queue was full).  Nothing was applied; the waiting worker's
# ``wait()`` raises a retryable ``OverloadError`` (back off and retry)
# instead of hanging, and completion callbacks are suppressed.
OPT_OVERLOAD = 7

# meta.option marker on an (empty) response: the receiving server does
# NOT own the request's key range under its current routing epoch
# (docs/elasticity.md — the worker raced a membership change with a
# stale table).  Nothing was applied; ``meta.val_len`` carries the
# server's epoch so the worker can pull a fresher table, and the
# deadline sweeper re-slices + re-routes the slice — never a hang,
# never a silent apply at the wrong server.
OPT_WRONG_OWNER = 8


@dataclass(frozen=True)
class CodecInfo:
    """Wire-compression extension (docs/compression.md): the payload's
    vals travel as ``[codes(u8), scales(f32)(, lens(i32))]`` encoded by
    the codec registry (``ops/codecs.py``).  Rides the tagged
    ``EXT_CODEC`` meta extension — NOT ``meta.option`` — so it composes
    with OPT_REPLICA forwards, OPT_ZPULL, and re-chunking, and the
    native lanes' template packing carries it untouched (EXT_CHUNK
    stays the trailing extension).

    On a pull REQUEST, ``raw_len == 0`` means "encode your response
    slice with this codec"; on a push request / pull response,
    ``raw_len`` is the uncompressed payload byte count the decoder
    sizes from."""

    codec: int = 0     # registry wire id (codecs.by_wire_id)
    raw_len: int = 0   # uncompressed vals byte count (0 = request)
    block: int = 0     # elements per scale block (0 = scale-free)
    flags: int = 0     # codecs.FLAG_* bits (e.g. int8 NaN sentinels)


@dataclass(frozen=True)
class BatchOp:
    """One sub-op of a multi-op batched frame (docs/batching.md).

    A batched frame's data section is the concatenation of its sub-ops'
    data segments in op order; ``nseg`` says how many segments this op
    consumed, so the decoder re-slices without byte arithmetic (the
    frame header's per-segment length table already delimits each
    segment).  Every sub-op keeps its OWN timestamp (completion
    accounting), key (slice identity), option (per-op error/overload
    codes on responses), hot-cache ``stamp``, and codec identity —
    batching changes how ops travel, never what they mean."""

    push: bool = False
    pull: bool = False
    timestamp: int = 0
    key: int = 0
    val_len: int = 0
    option: int = 0    # per-op response code (OPT_APPLY_ERROR/OVERLOAD)
    stamp: int = 0     # per-op hot-cache push-version (kv/hot_cache.py)
    nseg: int = 0      # data segments this op owns in the frame
    codec: Optional["CodecInfo"] = None
    # Per-op trace id (telemetry/tracing.py): traced ops MERGE like any
    # other — the id rides the table (packed only when nonzero, so
    # untraced frames are byte-identical to pre-trace builds) and is
    # echoed on the batched response, killing the old observer effect
    # where sampled ops were forced out of the batch plane.
    trace: int = 0


@dataclass(frozen=True)
class BatchInfo:
    """Multi-op aggregation extension (docs/batching.md): this frame
    carries ``len(ops)`` independent small KV ops to one destination.
    Rides the tagged ``EXT_BATCH`` meta extension (wire.py) with the
    per-op table serialized ahead of ``meta.body``; packed BEFORE
    EXT_CODEC/EXT_CHUNK so EXT_CHUNK stays the meta's trailing bytes
    (the native splitter's patch contract)."""

    ops: tuple = ()  # tuple[BatchOp]


@dataclass(frozen=True)
class ChunkInfo:
    """Chunked-transfer wire extension (docs/chunking.md): one large
    data message travels as ``total`` chunk messages, each carrying a
    contiguous byte range of the logical concatenation of the original
    data segments.  Every chunk repeats the segment table (lens +
    dtype codes) so reassembly can start from whichever chunk a
    multi-rail stripe lands first."""

    xfer: int = 0       # per-sender transfer id (unique per message)
    index: int = 0      # this chunk's position, 0..total-1
    total: int = 1      # chunks in the transfer
    offset: int = 0     # byte offset of this chunk in the logical stream
    seg_lens: tuple = ()   # original per-segment byte lengths
    seg_types: tuple = ()  # original per-segment wire dtype codes


def dtype_code(dt) -> int:
    return _DTYPE_TO_CODE.get(np.dtype(dt), 2)  # default: raw bytes


def code_dtype(code: int):
    return _CODE_TO_DTYPE.get(code, np.dtype(np.uint8))


@dataclass
class Node:
    """One process in the cluster (reference: message.h:66-134)."""

    role: Role = Role.SCHEDULER
    id: int = EMPTY_ID
    customer_id: int = 0
    hostname: str = ""
    ports: List[int] = field(default_factory=list)
    dev_types: List[int] = field(default_factory=list)
    dev_ids: List[int] = field(default_factory=list)
    is_recovery: bool = False
    # Opaque transport endpoint name (libfabric-style); unused by tcp/ici.
    endpoint_name: bytes = b""
    # Preferred rank (or transport-specific connection-tracking value).
    aux_id: int = EMPTY_ID

    @property
    def port(self) -> int:
        return self.ports[0] if self.ports else 0

    def addr_key(self) -> str:
        return f"{self.hostname}:{self.port}"

    def short_debug(self) -> str:
        return (
            f"[role={self.role.name}, id={self.id}, ip={self.hostname}, "
            f"ports={self.ports}, is_recovery={self.is_recovery}, "
            f"aux_id={self.aux_id}]"
        )


@dataclass
class Control:
    """System control plane payload (reference: message.h:136-175)."""

    cmd: Command = Command.EMPTY
    node: List[Node] = field(default_factory=list)
    barrier_group: int = 0
    msg_sig: int = 0

    def empty(self) -> bool:
        return self.cmd == Command.EMPTY


@dataclass
class Meta:
    """Message metadata (reference: message.h:177-258)."""

    head: int = EMPTY_ID
    app_id: int = EMPTY_ID
    customer_id: int = 0
    timestamp: int = EMPTY_ID
    sender: int = EMPTY_ID
    recver: int = EMPTY_ID
    request: bool = False
    push: bool = False
    pull: bool = False
    simple_app: bool = False
    # Transport-internal: payload rides out-of-band (shm segment descriptor
    # in body) rather than in the frame's data section.
    shm_data: bool = False
    body: bytes = b""
    data_type: List[int] = field(default_factory=list)
    control: Control = field(default_factory=Control)
    # Zero-copy routing: logical key, destination address token, value length,
    # transport option (rkey-equivalent), and per-peer sequence id.
    key: int = 0
    addr: int = 0
    val_len: int = 0
    option: int = 0
    sid: int = EMPTY_ID
    data_size: int = 0
    # Send-scheduling hint (KVPairs.priority): consumed by the sender's
    # PS_PRIORITY_SCHED heap, and carried on the wire so a server can
    # echo the request's priority into its (bulk) pull response.
    priority: int = 0
    # Distributed tracing (telemetry/tracing.py): nonzero = this request
    # was sampled; every process touching the message records lifecycle
    # spans against this id.  Travels as a backward-compatible wire
    # extension (wire.py) and is echoed on responses.
    trace: int = 0
    # Chunked streaming transfer (docs/chunking.md): non-None marks this
    # message as ONE chunk of a larger transfer.  Travels as a tagged
    # wire extension like ``trace`` — old decoders skip it by length.
    chunk: Optional[ChunkInfo] = None
    # Small-op aggregation (docs/batching.md): non-None marks this
    # frame as a MULTI-OP batch — N independent KV ops to one
    # destination, each with its own timestamp/key/option/stamp/codec
    # in the per-op table.  Request direction (worker op combiner) and
    # response direction (batched group responses + the server's
    # response combiner) share the layout; on responses the per-op
    # option/stamp carry result codes and hot-cache versions.  Tagged
    # EXT_BATCH extension; only ever sent to peers whose batch
    # capability was negotiated/proved (old decoders never see these
    # frames).
    batch: Optional[BatchInfo] = None
    # Wire compression (docs/compression.md): non-None marks the vals
    # payload as codec-encoded (or, on a pull request with raw_len=0,
    # asks the server to encode its response).  Tagged EXT_CODEC
    # extension, packed BEFORE the chunk extension so EXT_CHUNK stays
    # the meta's trailing bytes (the native splitter's patch contract).
    codec: Optional[CodecInfo] = None
    # Multi-tenant QoS (docs/qos.md): the named tenant this message's
    # traffic is accounted to — weighted-fair scheduling in the send
    # lanes / receive intake / apply shards, and per-tenant admission
    # control.  Travels with ``stamp`` in the tagged EXT_QOS extension
    # (packed only when either is nonzero, so default traffic's frames
    # are byte-identical to pre-tenant builds).
    tenant: int = 0
    # Server push-version stamp (kv/hot_cache.py): piggybacked on
    # responses so the worker-side hot-key cache can invalidate —
    # bumped after each push fully applies, echoed at a value every
    # concurrently-snapshotted pull is guaranteed to have observed.
    stamp: int = 0
    src_dev_type: int = int(DeviceType.UNK)
    src_dev_id: int = -1
    dst_dev_type: int = int(DeviceType.UNK)
    dst_dev_id: int = -1


@dataclass
class Message:
    """Meta plus zero-copy data segments (reference: message.h:260-301)."""

    meta: Meta = field(default_factory=Meta)
    data: List[SArray] = field(default_factory=list)

    def add_data(self, arr) -> None:
        sa = arr if isinstance(arr, SArray) else SArray(np.asarray(arr))
        self.data.append(sa)
        self.meta.data_type.append(dtype_code(sa.dtype))
        self.meta.data_size += sa.nbytes

    def debug_string(self) -> str:
        m = self.meta
        parts = [
            f"Meta: request={m.request}",
            f"timestamp={m.timestamp}",
            f"sender={m.sender}",
            f"recver={m.recver}",
        ]
        if not m.control.empty():
            parts.append(f"control={{cmd={m.control.cmd.name}, "
                         f"barrier_group={m.control.barrier_group}, "
                         f"nodes={[n.short_debug() for n in m.control.node]}}}")
        else:
            parts.append(
                f"app={m.app_id} customer={m.customer_id} push={m.push} "
                f"simple_app={m.simple_app} key={m.key}"
            )
        if m.body:
            parts.append(f"body={m.body[:64]!r}")
        if self.data:
            parts.append(f"data_bytes={[d.nbytes for d in self.data]}")
        return " ".join(parts)
