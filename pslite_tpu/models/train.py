"""PS-integrated SPMD training step for the flagship model.

One jit-compiled program over a ``(dp, sp)`` mesh:

1. **pull**: ``all_gather`` the flat parameter store (sharded over both
   axes — every device is a PS server shard) and unravel into the params
   pytree — the ``ZPull`` leg.
2. forward/backward with **ring attention over sp** (long context) on the
   local ``[B/dp, T/sp]`` token block — the worker compute.
3. **push**: ``psum_scatter`` of the flat gradient over ``(dp, sp)`` — the
   cross-worker aggregation ``KVServerDefaultHandle`` performs, executed as
   a collective (the ``ZPush`` leg).
4. **server update**: SGD applied to the local store shard.

This is the reference's async PS loop (docs/overview.md:44-125) re-derived
as a synchronous SPMD program — the "sync mode" SURVEY §7 requires, with
the async per-message mode still available through KVServer handlers.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

from .transformer import ModelConfig, init_params, loss_fn


def make_ps_train_step(cfg: ModelConfig, mesh, lr: float = 0.1,
                       seed: int = 0, sp_strategy: str = "ring"):
    """Returns (step_fn, flat_store, token_sharding, store_sharding).

    ``step_fn(flat_store, inputs, targets) -> (flat_store, loss)`` is jitted
    with donated store; inputs/targets are ``[B, T]`` int32 sharded
    ``P('dp', 'sp')``.

    ``sp_strategy`` picks the sequence-parallel attention: ``"ring"``
    (ppermute K/V ring, minimal residency) or ``"ulysses"`` (all-to-all
    head/sequence swap, 2 collectives — needs heads % sp == 0).
    """
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.ring_attention import ring_attention
    from ..parallel.ulysses import ulysses_attention
    from .ps_step import make_flat_ps_step
    from .transformer import ParallelCtx

    axes = tuple(mesh.axis_names)  # e.g. ('dp', 'sp')
    sp_axis = axes[-1]
    sp = mesh.shape[sp_axis]

    # Non-divisible shardings would silently drop feature columns /
    # experts inside shard_map; fail loudly up front instead.
    if cfg.moe_experts:
        if cfg.moe_experts % sp != 0:
            raise ValueError(
                f"moe_experts={cfg.moe_experts} must divide evenly over the "
                f"{sp}-way model axis"
            )
    elif (cfg.mlp_ratio * cfg.dim) % sp != 0:
        raise ValueError(
            f"mlp hidden width {cfg.mlp_ratio * cfg.dim} must divide evenly "
            f"over the {sp}-way model axis"
        )
    if sp_strategy not in ("ring", "ulysses"):
        raise ValueError(f"unknown sp_strategy {sp_strategy!r}")
    if sp_strategy == "ulysses" and cfg.heads % sp != 0:
        raise ValueError(
            f"ulysses needs heads ({cfg.heads}) divisible by the "
            f"{sp}-way sequence axis"
        )
    attn = ring_attention if sp_strategy == "ring" else ulysses_attention

    params0 = init_params(jax.random.PRNGKey(seed), cfg)

    def _local_loss(params, inp_l, tgt_l):
        sp_idx = lax.axis_index(sp_axis)
        t_local = inp_l.shape[1]
        # The model axis carries sequence parallelism (ring attention),
        # tensor parallelism (sharded MLP matmuls), and — for MoE configs —
        # expert parallelism, all at once.
        ctx = ParallelCtx(
            attn_fn=lambda q, k, v: attn(
                q, k, v, sp_axis, causal=True
            ),
            pos_offset=sp_idx * t_local,
            tp_axis=None if cfg.moe_experts else sp_axis,
            ep_axis=sp_axis if cfg.moe_experts else None,
        )
        return loss_fn(params, inp_l, tgt_l, cfg, ctx=ctx)

    token_spec = P(axes[0], sp_axis)
    step, flat_store, (token_sharding, _), store_sharding, _ = (
        make_flat_ps_step(
            mesh, params0, _local_loss, [token_spec, token_spec], lr=lr
        )
    )
    return step, flat_store, token_sharding, store_sharding


def make_pp_train_step(cfg: ModelConfig, mesh, lr: float = 0.1,
                       num_micro: int = 4, seed: int = 0):
    """PS training step with PIPELINE parallelism over the mesh's last
    axis (optionally data parallelism over a leading ``dp`` axis).

    The PS view: each pipeline stage owns the key range covering its
    layer block — the stacked layer params are sharded ``P('pp', ...)``
    and the stage-local SGD update IS the server-shard update (no
    cross-stage reduction exists because each stage is the sole owner of
    its range, the same invariant as key-range server sharding,
    postoffice.cc:257-268).  Replicated head params (embed / final norm)
    behave like a fully-replicated bucket: grads psum over pp (only the
    last stage holds non-zero head cotangents), pmean over dp, applied
    identically everywhere.

    Returns ``(step_fn, state, token_sharding)`` with
    ``state = (stacked_layers, head)`` already device_put onto the mesh;
    ``step_fn(state, inputs, targets) -> (state, loss)``; inputs/targets
    ``[dp, M, mb, T]`` int32 (microbatched along M).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import shard_map_compat as shard_map
    from ..parallel.pipeline import (
        pipeline_loss,
        stack_layers,
    )
    from .transformer import _rmsnorm

    axes = tuple(mesh.axis_names)
    pp_axis = axes[-1]
    S = mesh.shape[pp_axis]
    dp_axis = axes[0] if len(axes) > 1 else None
    if cfg.layers % S != 0:
        raise ValueError(
            f"layers={cfg.layers} must divide over the {S}-stage pipeline"
        )
    if cfg.moe_experts:
        raise ValueError("pp step supports dense layers only for now")

    params0 = init_params(jax.random.PRNGKey(seed), cfg)
    stacked0 = stack_layers(params0["layers"])
    head0 = {"embed": params0["embed"], "ln_f": params0["ln_f"]}

    D, H = cfg.dim, cfg.heads
    hd = D // H

    def _embed(head, tokens):
        x = head["embed"][tokens]  # [mb, T, D]
        T = x.shape[1]
        pos = jnp.arange(T)
        freqs = jnp.exp(-jnp.arange(0, D, 2) / D * jnp.log(10000.0))
        ang = pos[:, None] * freqs[None, :]
        pe = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
        return x + pe[None].astype(x.dtype)

    def _one_layer(layer, x):
        from ..parallel.ring_attention import reference_attention

        compute_dt = jnp.bfloat16 if x.dtype != jnp.float64 else x.dtype
        B, T, _ = x.shape
        h = _rmsnorm(x, layer["ln1"])
        qkv = (
            h.astype(compute_dt) @ layer["qkv"].astype(compute_dt)
        ).astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        o = reference_attention(
            q.reshape(B, T, H, hd),
            k.reshape(B, T, H, hd),
            v.reshape(B, T, H, hd),
            causal=True,
        ).reshape(B, T, D)
        x = x + (
            o.astype(compute_dt) @ layer["proj"].astype(compute_dt)
        ).astype(x.dtype)
        h = _rmsnorm(x, layer["ln2"])
        h1 = jax.nn.gelu(
            (h.astype(compute_dt) @ layer["mlp_in"].astype(compute_dt)
             ).astype(x.dtype)
        )
        return x + (
            h1.astype(compute_dt) @ layer["mlp_out"].astype(compute_dt)
        ).astype(x.dtype)

    def _stage_fn(stage_layers, x):
        def body(xc, layer):
            return _one_layer(layer, xc), None

        x, _ = lax.scan(body, x, stage_layers)
        return x

    def _head_loss(head, outs, tgt_micros):
        # outs: [M, mb, T, D] finished activations (last stage).
        compute_dt = jnp.bfloat16
        x = _rmsnorm(outs, head["ln_f"])
        logits = (
            x.astype(compute_dt) @ head["embed"].T.astype(compute_dt)
        ).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, tgt_micros[..., None], axis=-1
        )[..., 0]
        return nll.mean()

    def _local_step(stacked_l, head_r, inp_l, tgt_l):
        if dp_axis is not None:
            inp_l, tgt_l = inp_l[0], tgt_l[0]

        def _loss(sl, hr):
            x_micros = jax.vmap(lambda t: _embed(hr, t))(inp_l)
            return pipeline_loss(
                _stage_fn,
                lambda h, outs: _head_loss(h, outs, tgt_l),
                sl,
                hr,
                x_micros,
                pp_axis,
                S,
            )

        loss, (g_sl, g_hr) = jax.value_and_grad(_loss, argnums=(0, 1))(
            stacked_l, head_r
        )
        # Head grads live on the last stage only: sum over pp; average
        # both over dp replicas.
        g_hr = jax.tree.map(lambda g: lax.psum(g, pp_axis), g_hr)
        if dp_axis is not None:
            g_sl = jax.tree.map(lambda g: lax.pmean(g, dp_axis), g_sl)
            g_hr = jax.tree.map(lambda g: lax.pmean(g, dp_axis), g_hr)
            loss = lax.pmean(loss, dp_axis)
        new_sl = jax.tree.map(lambda p, g: p - lr * g, stacked_l, g_sl)
        new_hr = jax.tree.map(lambda p, g: p - lr * g, head_r, g_hr)
        return new_sl, new_hr, loss

    layer_spec = P(pp_axis)
    repl_spec = P()
    tok_spec = P(dp_axis) if dp_axis is not None else P(None)
    fn = shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(layer_spec, repl_spec, tok_spec, tok_spec),
        out_specs=(layer_spec, repl_spec, repl_spec),
    )
    jitted = jax.jit(fn, donate_argnums=(0, 1))

    def step(state, inputs, targets):
        sl, hr = state
        new_sl, new_hr, loss = jitted(sl, hr, inputs, targets)
        return (new_sl, new_hr), loss

    stacked = jax.device_put(
        stacked0,
        jax.tree.map(
            lambda _: NamedSharding(mesh, P(pp_axis)), stacked0
        ),
    )
    head = jax.device_put(
        head0, jax.tree.map(lambda _: NamedSharding(mesh, P()), head0)
    )
    token_sharding = NamedSharding(mesh, tok_spec)
    return step, (stacked, head), token_sharding


def kv_train_loop(worker, cfg: ModelConfig, steps: int = 30,
                  lr: float = 0.5, batch: int = 8, seq: int = 16,
                  codec=None, pull_codec="raw", seed: int = 0,
                  data_seed: int = 1, val_len: int = 1024):
    """Train the toy LM over the MESSAGE-PATH parameter server: the
    flat parameter vector lives in the KV store (``KVServerDefaultHandle``
    on the server side), and each step pulls params, computes the
    gradient locally (jit), and pushes ``-lr * grad`` as the delta —
    the async-PS loop of the reference, on the wire instead of the
    collective plane.

    ``codec`` compresses the gradient-delta PUSHES through the
    quantized transport tier (docs/compression.md) — the classic
    EF-SGD setting; ``pull_codec`` (default ``"raw"``) optionally
    compresses the parameter pulls too (each gradient is then computed
    at a perturbed point, which shifts the trajectory beyond what
    error feedback alone corrects — see the guard test).  The initial
    parameter seed always travels raw so compressed and uncompressed
    runs start from identical state.  This is the convergence-guard
    harness: with ``fp8_e4m3`` + error feedback the final loss must
    land within tolerance of the uncompressed run
    (tests/test_model_train.py).

    Returns the per-step loss list.
    """
    import jax
    import jax.flatten_util
    import jax.numpy as jnp
    import numpy as np

    from .transformer import loss_fn

    params0 = init_params(jax.random.PRNGKey(seed), cfg)
    flat0, unravel = jax.flatten_util.ravel_pytree(params0)
    flat0 = np.asarray(flat0, np.float32)
    n = flat0.size
    pad = (-n) % val_len
    flat_pad = np.concatenate([flat0, np.zeros(pad, np.float32)])
    keys = np.arange(flat_pad.size // val_len, dtype=np.uint64)

    @jax.jit
    def grad_fn(flat, inp, tgt):
        loss, g = jax.value_and_grad(
            lambda f: loss_fn(unravel(f[:n]), inp, tgt, cfg)
        )(flat)
        return loss, g

    inputs, targets = toy_batch(cfg, batch, seq, seed=data_seed)
    # Seed the store with the exact initial params (raw: both runs of a
    # comparison must start bit-identical), then train through the
    # registered bucket codec.
    worker.wait(worker.push(keys, flat_pad, codec="raw"))
    worker.register_bucket(keys, codec=codec)
    buf = np.empty_like(flat_pad)
    losses = []
    for _ in range(steps):
        worker.wait(worker.pull(keys, buf, codec=pull_codec))
        loss, g = grad_fn(jnp.asarray(buf), inputs, targets)
        # g is padded-length (grad of the padded flat vector; the pad
        # tail is exactly zero since loss only reads f[:n]).
        worker.wait(worker.push(keys, (-lr) * np.asarray(g, np.float32)))
        losses.append(float(loss))
    return losses


def toy_batch(cfg: ModelConfig, batch: int, seq: int, seed: int = 1):
    """Deterministic toy LM data: predict (token + 1) mod vocab."""
    import numpy as np

    rng = np.random.default_rng(seed)
    inputs = rng.integers(0, cfg.vocab, size=(batch, seq)).astype(np.int32)
    targets = (inputs + 1) % cfg.vocab
    return inputs, targets
