"""Critical-path attribution of assembled traces.

An assembled trace (telemetry/trace_store.py) is a bag of spans from
every node one request touched, on one wall-aligned timeline.  This
module turns it into the answer an operator actually needs when the
watchdog fires "req_p99 breached": a serial breakdown of the request's
wall time across the pipeline stages —

    worker queue → lane/combine wait → wire → server intake queue →
    decode → apply-shard wait → apply → response gate →
    response wire → completion

computed as CONSECUTIVE segments between the checkpoints the spans
provide.  For a fan-out request the breakdown follows the CRITICAL
server — the one whose response landed last; by construction the
stages of one trace sum exactly to the request's measured wall time
(missing checkpoints fold their interval into the next present stage,
never drop it).
"""

from __future__ import annotations

from typing import Dict, List, Optional

# Stage names in pipeline order.  Every breakdown dict carries all of
# them (0.0 where the trace had no checkpoint to split on).
STAGES = (
    "worker_queue",    # issue -> send-lane/combiner enqueue
    "lane_wait",       # enqueue -> dispatch (lane_wait / combine_wait)
    "wire",            # dispatch -> server receive
    "server_queue",    # server receive -> request-thread intake
    "decode",          # codec decode (0 for raw payloads)
    "apply_wait",      # intake -> first apply-shard start
    "apply",           # first apply start -> last apply end
    "response_gate",   # apply end -> response emission (order gate)
    "response_wire",   # respond -> worker receives the response
    "completion",      # response receive -> request completion
)


def _end(ev: dict) -> float:
    return ev.get("ts", 0.0) + ev.get("dur", 0.0)


def _server_events(spans: List[dict], wpid: int,
                   t0: float, t1: float) -> Dict[int, dict]:
    """Per-server-node checkpoint spans within the request window."""
    out: Dict[int, dict] = {}
    for ev in spans:
        pid = ev.get("pid")
        if pid == wpid:
            continue
        ts = ev.get("ts", 0.0)
        if ts < t0 - 1.0 or ts > t1 + 1.0:
            continue  # an earlier retry's spans under a reused ring id
        ent = out.setdefault(pid, {})
        name = ev.get("name")
        if name == "server_queue" and "sq" not in ent:
            ent["sq"] = ev
        elif name == "codec_decode":
            ent["decode"] = ev
        elif name == "apply":
            ent.setdefault("applies", []).append(ev)
        elif name == "respond":
            # Batched frames respond once per sub-op; keep the first.
            if "respond" not in ent:
                ent["respond"] = ev
    return out


def breakdown(trace) -> Optional[dict]:
    """Per-stage attribution of one assembled trace; None without a
    worker root span."""
    root = trace.root
    if root is None:
        return None
    wpid = root.get("pid")
    t0 = root.get("ts", 0.0)
    wall = root.get("dur", 0.0)
    t1 = t0 + wall
    args = root.get("args") or {}
    # Worker-side send checkpoint: the earliest lane/combiner wait
    # inside the window (a fan-out's first slice — the critical chain
    # below is server-side; send-side skew is sub-stage noise).
    lane = None
    wrecv = None
    for ev in trace.spans:
        if ev.get("pid") != wpid:
            continue
        ts = ev.get("ts", 0.0)
        if ts < t0 - 1.0 or ts > t1 + 1.0:
            continue
        name = ev.get("name")
        if name in ("lane_wait", "combine_wait"):
            if lane is None or ts < lane["ts"]:
                lane = ev
        elif name == "recv" and not (ev.get("args") or {}).get("request",
                                                               True):
            # The LAST response frame's arrival bounds response_wire.
            if wrecv is None or ts > wrecv["ts"]:
                wrecv = ev
    servers = _server_events(trace.spans, wpid, t0, t1)
    critical = None
    for pid, ent in servers.items():
        marks = [
            _end(e) for e in (
                [ent.get("respond")]
                + (ent.get("applies") or [])
                + [ent.get("sq")]
            ) if e is not None
        ]
        if not marks:
            continue
        ent["last"] = max(marks)
        ent["pid"] = pid
        if critical is None or ent["last"] > critical["last"]:
            critical = ent
    # Checkpoints in pipeline order: (stage ending here, time).
    checkpoints: List[tuple] = []
    if lane is not None:
        checkpoints.append(("worker_queue", lane["ts"]))
        checkpoints.append(("lane_wait", _end(lane)))
    if critical is not None:
        sq = critical.get("sq")
        if sq is not None:
            checkpoints.append(("wire", sq["ts"]))
            checkpoints.append(("server_queue", _end(sq)))
        dec = critical.get("decode")
        if dec is not None:
            checkpoints.append(("decode", _end(dec)))
        applies = critical.get("applies") or []
        if applies:
            checkpoints.append(("apply_wait",
                                min(e["ts"] for e in applies)))
            checkpoints.append(("apply", max(_end(e) for e in applies)))
        resp = critical.get("respond")
        if resp is not None:
            checkpoints.append(("response_gate", resp["ts"]))
    if wrecv is not None:
        checkpoints.append(("response_wire", wrecv["ts"]))
    stages = {name: 0.0 for name in STAGES}
    prev = t0
    for name, c in checkpoints:
        c = min(max(c, prev), t1)  # clamp: monotone, inside the window
        stages[name] += c - prev
        prev = c
    stages["completion"] += t1 - prev  # remainder: sum == wall exactly
    return {
        "trace": trace.tid,
        "wall_us": wall,
        "t0_us": t0,
        "worker": wpid,
        "server": critical["pid"] if critical is not None else None,
        "keep": args.get("keep"),
        "outcome": args.get("outcome"),
        "pull": args.get("pull"),
        "stages": stages,
        "flight": list(getattr(trace, "flight", ())),
    }


def _stage_shares(rows: List[dict]) -> dict:
    totals = {name: 0.0 for name in STAGES}
    for b in rows:
        for name, v in b["stages"].items():
            totals[name] += v
    wall = sum(totals.values())
    return {
        name: {"total_us": round(totals[name], 1),
               "share": round(totals[name] / wall, 4) if wall > 0 else 0.0}
        for name in STAGES
    }


def aggregate(breakdowns: List[dict], slow_frac: float = 0.25) -> dict:
    """"Where does the tail live": per-stage totals and shares across
    all assembled traces, plus the same table restricted to the
    SLOWEST ``slow_frac`` of them (the population a p99 panel shows).
    ``top_stage`` names the slow set's dominant stage — the pstrace
    headline."""
    if not breakdowns:
        return {"count": 0, "stages": {}, "slow": {}, "top_stage": None,
                "wall_p50_us": 0.0, "wall_max_us": 0.0}
    by_wall = sorted(breakdowns, key=lambda b: b["wall_us"])
    n = len(by_wall)
    slow = by_wall[max(0, n - max(1, round(n * slow_frac))):]
    stages = _stage_shares(breakdowns)
    slow_stages = _stage_shares(slow)
    top = max(slow_stages, key=lambda s: slow_stages[s]["total_us"])
    return {
        "count": n,
        "wall_p50_us": round(by_wall[n // 2]["wall_us"], 1),
        "wall_max_us": round(by_wall[-1]["wall_us"], 1),
        "stages": stages,
        "slow": slow_stages,
        "slow_count": len(slow),
        "top_stage": top,
    }
