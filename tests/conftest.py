"""Test bootstrap: force the CPU backend with 8 virtual devices.

Sharding/collective tests run on a virtual 8-device CPU mesh; real-TPU
benchmarking happens in bench.py (which does NOT import this).
"""

import os

# Hard-set: the environment may preset JAX_PLATFORMS to the real TPU
# (e.g. "axon"); unit tests always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize (TPU tunnel) may have already forced
# jax_platforms programmatically at interpreter start; override before the
# first backend use so tests stay on the 8-device virtual CPU mesh.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # jax-less host: non-jax tests still run
    pass

import pytest

# Best-effort build of the native transport core so the suite exercises the
# C++ path; tests still pass on the pure-Python fallback if g++ is missing.
_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if not os.path.exists(os.path.join(_repo, "cpp", "libpslite_core.so")):
    import subprocess

    subprocess.run(
        ["make", "-C", os.path.join(_repo, "cpp")],
        capture_output=True,
        check=False,
    )


@pytest.fixture(autouse=True)
def _loopback_isolation(request):
    """Give each test its own loopback namespace and clean registry."""
    os.environ["PS_LOOPBACK_NS"] = request.node.nodeid
    yield
    from pslite_tpu.vans import loopback_van

    loopback_van.reset_registry()
    os.environ.pop("PS_LOOPBACK_NS", None)
