"""Pallas kernels: fused optimizer updates and int8 quantization
(interpreter mode on the CPU mesh; the same code compiles on TPU)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from pslite_tpu.ops import (
    adam_update,
    dequantize_int8,
    quantize_int8,
    sgd_update,
)


def test_sgd_update_matches_reference():
    rng = np.random.default_rng(0)
    n = 3000  # not block-aligned
    store = rng.normal(size=n).astype(np.float32)
    mom = rng.normal(size=n).astype(np.float32)
    agg = rng.normal(size=n).astype(np.float32)

    new_store, new_mom = sgd_update(
        jnp.asarray(store), jnp.asarray(mom), jnp.asarray(agg),
        lr=0.1, momentum=0.9,
    )
    ref_mom = 0.9 * mom + agg
    ref_store = store - 0.1 * ref_mom
    np.testing.assert_allclose(np.asarray(new_mom), ref_mom, rtol=1e-6,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_store), ref_store, rtol=1e-6,
                               atol=1e-6)


def test_adam_update_matches_reference():
    rng = np.random.default_rng(1)
    n = 2048
    store = rng.normal(size=n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    agg = rng.normal(size=n).astype(np.float32)
    lr, b1, b2, eps = 1e-2, 0.9, 0.999, 1e-8

    new_store, new_m, new_v = adam_update(
        jnp.asarray(store), jnp.asarray(m), jnp.asarray(v),
        jnp.asarray(agg), step=1, lr=lr, beta1=b1, beta2=b2, eps=eps,
    )
    ref_m = (1 - b1) * agg
    ref_v = (1 - b2) * agg * agg
    alpha = lr * np.sqrt(1 - b2) / (1 - b1)
    ref_store = store - alpha * ref_m / (np.sqrt(ref_v) + eps)
    np.testing.assert_allclose(np.asarray(new_m), ref_m, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(new_v), ref_v, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(new_store), ref_store, rtol=1e-4,
                               atol=1e-6)


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(2)
    n = 5000
    x = (rng.normal(size=n) * 10).astype(np.float32)
    q, scales = quantize_int8(jnp.asarray(x))
    assert q.dtype == jnp.int8
    out = np.asarray(dequantize_int8(q, scales, n))
    # Error bounded by half a quantization step per 128-lane row.
    per_elem_scale = np.repeat(np.asarray(scales)[:, 0], 128)[:n]
    assert np.all(np.abs(out - x) <= per_elem_scale * 0.5 + 1e-6)
    # Wire form: int8 payload + one fp32 scale per row => ~4x smaller.
    wire = q.nbytes + np.asarray(scales)[:, 0].nbytes
    assert wire * 3 <= x.nbytes + 4 * 128 * 32 * 4
    # Compact wire scales round-trip too.
    out2 = np.asarray(
        dequantize_int8(q, np.asarray(scales)[:, 0].copy(), n)
    )
    np.testing.assert_allclose(out2, out)


def test_quantize_zero_input():
    x = jnp.zeros(1024, jnp.float32)
    q, s = quantize_int8(x)
    out = dequantize_int8(q, s, 1024)
    np.testing.assert_array_equal(np.asarray(out), 0)


# -- wire codec registry (ops/codecs.py — docs/compression.md) ---------------


def _codec_names():
    from pslite_tpu.ops import codecs

    return codecs.names()


@pytest.mark.parametrize("name", ["int8", "fp8_e4m3", "bf16"])
def test_codec_roundtrip_error_bounded(name):
    """Property: decode(encode(x)) lands within the codec's per-block
    quantization step, for aligned and ragged lengths."""
    from pslite_tpu.ops import codecs

    if name not in _codec_names():
        pytest.skip(f"{name} unavailable (ml_dtypes)")
    c = codecs.get_codec(name)
    rng = np.random.default_rng(3)
    for n in (128, 127, 5000, 65536 + 17):
        x = (rng.normal(size=n) * 10).astype(np.float32)
        codes, scales, flags = c.encode(x)
        out = c.decode(np.ascontiguousarray(codes), scales, n,
                       flags=flags)
        if name == "bf16":
            # RNE to 8 mantissa bits: relative error <= 2^-9.
            assert np.all(np.abs(out - x) <= np.abs(x) * 2.0 ** -8 + 1e-30)
            assert codes.nbytes == 2 * n and scales.size == 0
        else:
            starts = np.arange(0, n, codecs.BLOCK)
            step = np.maximum.reduceat(np.abs(x), starts) / (
                127.0 if name == "int8" else 448.0
            )
            sizes = np.diff(np.append(starts, n))
            per_elem = np.repeat(step, sizes)
            # int8 rounds to the nearest step; fp8 keeps ~3 mantissa
            # bits of the scaled value (error < max(step, |x|/16)).
            bound = (per_elem * 0.51 if name == "int8"
                     else np.maximum(per_elem, np.abs(x) / 14.0))
            assert np.all(np.abs(out - x) <= bound + 1e-7), name
            assert codes.nbytes == n
            assert scales.size == (n + 127) // 128


@pytest.mark.parametrize("name", ["int8", "fp8_e4m3", "bf16"])
def test_codec_ragged_per_key_blockwise(name):
    """lens payloads scale PER KEY: a huge-magnitude key must not
    flatten a small-magnitude neighbour's resolution."""
    from pslite_tpu.ops import codecs

    if name not in _codec_names():
        pytest.skip(f"{name} unavailable")
    c = codecs.get_codec(name)
    rng = np.random.default_rng(4)
    lens = np.array([1, 127, 128, 129, 700], np.int64)
    small = rng.normal(size=int(lens[:-1].sum())).astype(np.float32)
    huge = (rng.normal(size=int(lens[-1])) * 1e6).astype(np.float32)
    x = np.concatenate([small, huge])
    codes, scales, flags = c.encode(x, lens=lens)
    out = c.decode(np.ascontiguousarray(codes), scales, x.size,
                   lens=lens, flags=flags)
    # The small keys' error must be set by THEIR own block maxes, not
    # the 1e6 neighbour (a shared scale would give errors ~1e6/127).
    assert np.abs(out[: small.size] - small).max() < 0.2, name
    if name != "bf16":
        assert scales.size == int(
            ((lens + codecs.BLOCK - 1) // codecs.BLOCK).sum()
        )


@pytest.mark.parametrize("name", ["int8", "fp8_e4m3", "bf16"])
def test_codec_nan_inf_policy(name):
    """Policy (docs/compression.md): NaN propagates through every
    codec; +/-Inf saturates to the block max (bf16 keeps Inf); scales
    are computed over FINITE values only, so one bad element cannot
    zero its block's resolution."""
    from pslite_tpu.ops import codecs

    if name not in _codec_names():
        pytest.skip(f"{name} unavailable")
    c = codecs.get_codec(name)
    x = np.linspace(-4, 4, 512).astype(np.float32)
    x[10], x[200], x[300] = np.nan, np.inf, -np.inf
    codes, scales, flags = c.encode(x)
    out = c.decode(np.ascontiguousarray(codes), scales, x.size,
                   flags=flags)
    assert np.isnan(out[10]), name
    if name == "bf16":
        assert out[200] == np.inf and out[300] == -np.inf
    else:
        # Saturated to the FINITE block max (scale unpoisoned).
        assert np.isfinite(out[200]) and out[200] > 0
        assert np.isfinite(out[300]) and out[300] < 0
        # The rest of the NaN/Inf blocks kept their resolution.
        fin = np.isfinite(x)
        assert np.abs(out[fin] - x[fin]).max() < 0.5


@pytest.mark.parametrize("name", ["int8", "fp8_e4m3", "bf16"])
def test_codec_empty_vals_rejected(name):
    from pslite_tpu.ops import codecs

    if name not in _codec_names():
        pytest.skip(f"{name} unavailable")
    with pytest.raises(ValueError):
        codecs.get_codec(name).encode(np.empty(0, np.float32))


def test_codec_native_kernel_bit_identical_to_numpy():
    """The C fused kernels (psl_codec_encode/decode — mixed clusters
    depend on this) must produce byte-identical codes, scales, decodes
    AND error-feedback residuals to the numpy fallback."""
    from pslite_tpu.ops import codecs

    if codecs._native_codec() is None:
        pytest.skip("native codec kernels unavailable (make native)")
    rng = np.random.default_rng(5)
    try:
        for name in ("int8", "fp8_e4m3"):
            if name not in _codec_names():
                continue
            c = codecs.get_codec(name)
            for scale_f in (1.0, 1e6, 1e-9):
                x = (rng.normal(size=300_017) * scale_f).astype(
                    np.float32
                )
                x[7], x[13], x[17] = np.nan, np.inf, -np.inf
                co_n, sc_n, fl_n = c.encode(x)
                co_n = bytes(co_n)
                o_n = c.decode(np.frombuffer(co_n, np.uint8), sc_n,
                               x.size, flags=fl_n).copy()
                rn = np.zeros(x.size, np.float32)
                c.encode(x, resid=rn)
                codecs._native_lib = None  # force the numpy fallback
                co_p, sc_p, fl_p = c.encode(x)
                o_p = c.decode(np.ascontiguousarray(co_p), sc_p,
                               x.size, flags=fl_p).copy()
                rp = np.zeros(x.size, np.float32)
                c.encode(x, resid=rp)
                codecs._native_probed = False
                codecs._native_codec()
                assert bytes(co_p) == co_n and fl_p == fl_n, name
                assert np.array_equal(np.asarray(sc_p),
                                      np.asarray(sc_n)), name
                assert np.array_equal(o_p, o_n, equal_nan=True), name
                assert np.array_equal(rn, rp), name
    finally:
        codecs._native_probed = False
        codecs._native_codec()


def test_error_feedback_removes_quantization_bias():
    """The EF mechanism (docs/compression.md): repeatedly quantizing
    the SAME gradient without EF leaves a persistent bias (components
    below the quantization step round to zero forever); with the
    residual folded back in, the mean of the decoded stream converges
    to the true value."""
    from pslite_tpu.ops import codecs

    c = codecs.get_codec("int8")
    rng = np.random.default_rng(6)
    # One dominant component per block pushes the others under the
    # step — the no-EF worst case.
    x = (rng.normal(size=4096) * 0.01).astype(np.float32)
    x[::128] = 10.0
    rounds = 64
    resid = np.zeros(x.size, np.float32)
    acc_ef = np.zeros_like(x)
    acc_raw = np.zeros_like(x)
    for _ in range(rounds):
        co, sc, fl = c.encode(x, resid=resid)
        acc_ef += c.decode(np.ascontiguousarray(co), sc, x.size,
                           flags=fl)
        co, sc, fl = c.encode(x)
        acc_raw += c.decode(np.ascontiguousarray(co), sc, x.size,
                            flags=fl)
    err_ef = np.abs(acc_ef / rounds - x).max()
    err_raw = np.abs(acc_raw / rounds - x).max()
    # Without EF the small components are ALL zero forever (bias =
    # their full magnitude); with EF the mean error shrinks ~rounds-x.
    assert err_raw > 0.009, err_raw  # the bias is real
    assert err_ef < err_raw / 10, (err_ef, err_raw)


def test_error_feedback_bank_bounded_and_evicts_loudly():
    """ErrorFeedback slots are bounded; exceeding the cap evicts LRU
    with a loud log, and a size change under the same key resets the
    slot."""
    import logging

    from pslite_tpu.ops import codecs

    bank = codecs.ErrorFeedback(max_slots=2)
    r1, _ = bank.slot(("a",), 8)
    r1[:] = 1.0
    bank.slot(("b",), 8)
    assert len(bank) == 2
    # The repo logger does not propagate; attach a capture handler.
    msgs = []
    h = logging.Handler()
    h.emit = lambda rec: msgs.append(rec.getMessage())
    logging.getLogger("pslite_tpu").addHandler(h)
    try:
        bank.slot(("c",), 8)  # evicts "a" (LRU)
    finally:
        logging.getLogger("pslite_tpu").removeHandler(h)
    assert len(bank) == 2
    assert bank.evictions == 1
    assert any("error-feedback" in m for m in msgs)
    # "a" comes back zeroed (its residual was genuinely dropped).
    r1b, _ = bank.slot(("a",), 8)
    assert not r1b.any()
    # Same key, new size: slot resets rather than aliasing stale data.
    r2, _ = bank.slot(("b",), 16)
    assert r2.size == 16 and not r2.any()
    assert bank.residual_norm() >= 0.0


def test_adagrad_update_matches_reference():
    from pslite_tpu.ops.fused_update import adagrad_update

    rng = np.random.default_rng(3)
    n = 3000  # not block-aligned
    store = rng.normal(size=n).astype(np.float32)
    acc = np.abs(rng.normal(size=n)).astype(np.float32)
    agg = rng.normal(size=n).astype(np.float32)
    lr, eps = 0.05, 1e-8

    new_store, new_acc = adagrad_update(
        jnp.asarray(store), jnp.asarray(acc), jnp.asarray(agg),
        lr=lr, eps=eps,
    )
    ref_acc = acc + agg * agg
    ref_store = store - lr * agg / (np.sqrt(ref_acc) + eps)
    np.testing.assert_allclose(np.asarray(new_acc), ref_acc, rtol=1e-6,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_store), ref_store, rtol=1e-5,
                               atol=1e-6)
