"""PS_FORCE_REQ_ORDER: per-peer in-order delivery of data messages
(UCX-van sid/reorder parity, ucx_van.h:1032-1039, 1217-1257) — plus the
send-lane guarantee those sids rest on: per-recver sid monotonicity on
the wire while lanes to several peers dispatch concurrently."""

import collections
import threading

import numpy as np

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker, KVPairs
from pslite_tpu.base import EMPTY_ID
from pslite_tpu.message import Message, Meta

from helpers import LoopbackCluster


def test_in_order_delivery_under_shuffle():
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={"PS_FORCE_REQ_ORDER": "1"},
    )
    cluster.start()
    servers = []
    try:
        order = []

        class RecordingHandle:
            def __call__(self, meta, data, server):
                if meta.push:
                    order.append(int(data.vals[0]))
                    server.response(meta)
                else:
                    server.response(
                        meta,
                        KVPairs(keys=data.keys,
                                vals=np.zeros(1, np.float32)),
                    )

        srv = KVServer(0, postoffice=cluster.servers[0])
        srv.set_request_handle(RecordingHandle())
        servers.append(srv)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])

        # Issue several pushes; the van assigns consecutive sids.
        keys = np.array([1], dtype=np.uint64)
        tss = [
            worker.push(keys, np.full(4, float(i), np.float32))
            for i in range(6)
        ]
        for ts in tss:
            worker.wait(ts)
        assert order == [float(i) for i in range(6)]

        # The reorder buffer releases a stalled-then-arrived sid in order.
        van = cluster.servers[0].van
        sender = cluster.workers[0].van.my_node.id
        expected = van._recv_expected[sender]

        def data_msg(sid, tag):
            m = Message()
            m.meta = Meta(app_id=0, customer_id=0, timestamp=99,
                          sender=sender, recver=van.my_node.id,
                          request=True, push=True, sid=sid)
            m.add_data(np.array([1], np.uint64))
            m.add_data(np.full(4, tag, np.float32))
            return m

        out_of_order = van._release_in_order(data_msg(expected + 1, 101.0))
        assert out_of_order == []  # buffered, not delivered
        released = van._release_in_order(data_msg(expected, 100.0))
        assert [float(r.data[1].numpy()[0]) for r in released] == [100.0, 101.0]
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_fanout_sid_monotonic_per_peer():
    """Per-recver sid monotonicity ON THE WIRE while ≥3 peers receive
    concurrently: several app threads push through the same van, whose
    per-peer send lanes dispatch to 3 servers in parallel — each
    recver's sid sequence must still be exactly 0, 1, 2, … in wire
    order (sids are assigned at dispatch time, under the lane)."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=3,
        env_extra={"PS_FORCE_REQ_ORDER": "1"},
    )
    cluster.start()
    servers = []
    try:
        for po in cluster.servers:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        van = cluster.workers[0].van
        wire_sids = collections.defaultdict(list)
        wire_mu = threading.Lock()
        orig = van.send_msg

        def spying(msg):
            if msg.meta.control.empty():
                with wire_mu:
                    wire_sids[msg.meta.recver].append(msg.meta.sid)
            return orig(msg)

        van.send_msg = spying
        try:
            ranges = cluster.workers[0].get_server_key_ranges()
            # Keys spanning all 3 server ranges: every push fans out to
            # every server (3 concurrent lanes per push).
            keys = np.array(sorted(r.begin + 1 for r in ranges),
                            dtype=np.uint64)
            n_threads, n_pushes = 4, 8
            workers = [
                KVWorker(0, cid, postoffice=cluster.workers[0])
                for cid in range(n_threads)
            ]
            errs = []

            def pusher(kv):
                try:
                    vals = np.ones(len(keys) * 4, np.float32)
                    for ts in [kv.push(keys, vals)
                               for _ in range(n_pushes)]:
                        kv.wait(ts)
                except Exception as exc:  # noqa: BLE001 - surfaced below
                    errs.append(exc)

            threads = [threading.Thread(target=pusher, args=(kv,),
                                        daemon=True) for kv in workers]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errs, errs
        finally:
            van.send_msg = orig
        server_ids = {po.van.my_node.id for po in cluster.servers}
        assert server_ids <= set(wire_sids)
        for recver in server_ids:
            sids = wire_sids[recver]
            # Strictly consecutive from 0: monotonic, no gaps, no dups.
            assert sids == list(range(len(sids))), (recver, sids)
            assert len(sids) >= n_threads * n_pushes
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()
