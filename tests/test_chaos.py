"""Chaos van scenarios (docs/fault_tolerance.md): the seeded ``PS_CHAOS``
injector — drops, delays, reorders, duplicates, one-way partitions, and
crash-at-phase hooks — wrapped around the loopback transport, proving
the reliability tiers (resender, deadlines, failure detector) against
hostile links.
"""

import time

import numpy as np
import pytest

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.base import server_rank_to_id, worker_rank_to_id
from pslite_tpu.vans.chaos_van import ChaosPolicy, parse_spec
from pslite_tpu.utils.logging import CheckError

from helpers import LoopbackCluster


def test_spec_grammar():
    spec = parse_spec(
        "seed=42,drop=0.2,send_drop=0.1,delay=1:20,send_delay=5,"
        "reorder=0.1,dup=0.05,part=9>8;8>9,crash=recv:50"
    )
    assert spec["seed"] == 42
    assert spec["drop"] == 0.2
    assert spec["send_drop"] == 0.1
    assert spec["delay"] == (0.001, 0.02)
    assert spec["send_delay"] == (0.005, 0.005)
    assert spec["reorder"] == 0.1
    assert spec["dup"] == 0.05
    assert spec["partitions"] == {(9, 8), (8, 9)}
    assert spec["crash_phase"] == "recv"
    assert spec["crash_after"] == 50
    assert parse_spec("")["crash_phase"] is None
    for bad in ("drop=1.5", "crash=apply:3", "frob=1", "drop"):
        with pytest.raises(CheckError):
            parse_spec(bad)


def test_policy_seeded_determinism():
    """Same seed + node id => identical decision stream (scenarios
    replay bit-identically); different node ids diverge."""
    a = ChaosPolicy("seed=7,drop=0.5")
    b = ChaosPolicy("seed=7,drop=0.5")
    c = ChaosPolicy("seed=7,drop=0.5")
    seq_a = [a.draw(9, "drop") for _ in range(64)]
    seq_b = [b.draw(9, "drop") for _ in range(64)]
    seq_c = [c.draw(11, "drop") for _ in range(64)]
    assert seq_a == seq_b
    assert seq_a != seq_c


def test_crash_counter_phases():
    p = ChaosPolicy("crash=recv:2")
    for _ in range(2):
        p.count_data("recv")
    assert not p.crashed.is_set()
    p.count_data("send")  # wrong phase: no effect
    assert not p.crashed.is_set()
    p.count_data("recv")
    assert p.crashed.is_set()
    assert p.crash_blocks("recv") and not p.crash_blocks("send")


def test_chaos_matrix_healed_by_resender():
    """drop + delay + reorder + dup on every node, healed end-to-end by
    PS_RESEND acks/retransmits/dedup: the store still sums exactly."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=2, van_type="chaos+loopback",
        env_extra={
            "PS_CHAOS": "seed=11,drop=0.15,delay=0.5:2,reorder=0.1,dup=0.1",
            "PS_RESEND": "1",
            "PS_RESEND_TIMEOUT": "60",
        },
    )
    cluster.start()
    servers = []
    try:
        for po in cluster.servers:
            s = KVServer(0, postoffice=po)
            s.set_request_handle(KVServerDefaultHandle())
            servers.append(s)
        worker = KVWorker(0, 0, postoffice=cluster.workers[0])
        keys = np.array([3, 2**63 + 9], dtype=np.uint64)  # both ranges
        vals = np.ones(32, dtype=np.float32)
        rounds = 8
        for _ in range(rounds):
            worker.wait(worker.push(keys, vals))
        out = np.zeros_like(vals)
        worker.wait(worker.pull(keys, out))
        np.testing.assert_allclose(out, rounds * vals)
        injected = sum(
            sum(po.van.chaos_stats.values()) for po in cluster.all_nodes()
        )
        assert injected > 0, "chaos injected nothing — spec inert?"
        worker.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_oneway_partition_times_out():
    """A one-way partition worker->server starves the request path even
    though responses/acks could flow back: the resender exhausts and the
    wait fails with TimeoutError instead of hanging."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="chaos+loopback",
        env_extra={
            "PS_CHAOS": f"part={worker_rank_to_id(0)}>{server_rank_to_id(0)}",
            "PS_RESEND": "1",
            "PS_RESEND_TIMEOUT": "40",
        },
    )
    cluster.start()
    srv = KVServer(0, postoffice=cluster.servers[0])
    srv.set_request_handle(KVServerDefaultHandle())
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            worker.wait(worker.push(np.array([3], dtype=np.uint64),
                                    np.ones(8, dtype=np.float32)))
        assert time.monotonic() - t0 < 30.0
        # The edge is cut at the sender: the worker's van swallowed the
        # sends (the server-side recv filter covers asymmetric deploys
        # where only one endpoint carries the spec).
        assert cluster.workers[0].van.chaos_stats["send_partitioned"] > 0
        assert cluster.servers[0].van.chaos_stats["recv_partitioned"] == 0
    finally:
        worker.stop()
        srv.stop()
        for po in cluster.all_nodes():
            po.van.stop()


def test_crash_hook_deaf_server_detected_and_bounded():
    """crash=recv:N — after N data messages the server goes deaf and
    stops heartbeating: later requests time out within their deadline
    budget, and the scheduler's detector declares the node dead."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="chaos+loopback",
        env_extra={
            "PS_HEARTBEAT_INTERVAL": "0.3",
            "PS_HEARTBEAT_TIMEOUT": "1.0",
            "PS_REQUEST_TIMEOUT": "0.3",
            "PS_REQUEST_RETRIES": "1",
        },
        per_node_env={"server0": {"PS_CHAOS": "crash=recv:3"}},
    )
    cluster.start()
    srv = KVServer(0, postoffice=cluster.servers[0])
    srv.set_request_handle(KVServerDefaultHandle())
    worker = KVWorker(0, 0, postoffice=cluster.workers[0])
    keys = np.array([3], dtype=np.uint64)
    vals = np.ones(8, dtype=np.float32)
    try:
        for _ in range(3):
            worker.wait(worker.push(keys, vals))
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            worker.wait(worker.push(keys, vals))
        assert time.monotonic() - t0 < 10.0
        assert cluster.servers[0].van.chaos_crashed.is_set()
        deadline = time.monotonic() + 15
        while (not cluster.scheduler.get_dead_nodes(1.0)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert server_rank_to_id(0) in cluster.scheduler.get_dead_nodes(1.0)
        assert cluster.servers[0].van.chaos_stats["heartbeat_suppressed"] > 0
    finally:
        worker.stop()
        srv.stop()
        for po in cluster.all_nodes():
            po.van.stop()
