"""Thread-safe queue used by vans and customers.

Equivalent of the reference's ``ThreadsafeQueue``
(``include/ps/internal/threadsafe_queue.h:18-118``): a mutex+condvar MPMC
queue, with an optional busy-poll mode (``DMLC_LOCKLESS_QUEUE`` /
``DMLC_POLLING_IN_NANOSECOND``) that trades CPU for latency on the hot
receive path.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, Generic, Optional, TypeVar

T = TypeVar("T")


class ThreadsafeQueue(Generic[T]):
    def __init__(self, busy_poll_ns: int = 0):
        self._q: Deque[T] = collections.deque()
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # Busy-poll window before falling back to a blocking wait.
        self._busy_poll_s = busy_poll_ns / 1e9

    def push(self, item: T) -> None:
        with self._cv:
            self._q.append(item)
            self._cv.notify()

    def wait_and_pop(self, timeout: Optional[float] = None) -> Optional[T]:
        """Pop the next item, blocking.  Returns None on timeout."""
        if self._busy_poll_s > 0:
            deadline = time.monotonic() + self._busy_poll_s
            while time.monotonic() < deadline:
                with self._mu:
                    if self._q:
                        return self._q.popleft()
        with self._cv:
            if timeout is None:
                while not self._q:
                    self._cv.wait()
            else:
                deadline = time.monotonic() + timeout
                while not self._q:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        if not self._q:
                            return None
            return self._q.popleft()

    def try_pop(self) -> Optional[T]:
        with self._mu:
            return self._q.popleft() if self._q else None

    def __len__(self) -> int:
        with self._mu:
            return len(self._q)
