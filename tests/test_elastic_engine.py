"""Engine elastic tier: reshard live dense buckets and sparse tables onto
a different mesh (scale the server fleet up/down) without losing state.

The reference's elasticity is roster-level (dead-id inheritance,
van.cc:266-332; keepalive restart); on the collective data plane the
roster IS the mesh, so the equivalent capability is state-preserving
resharding with key ranges recut for the new shard count
(postoffice.cc:257-268 semantics).
"""

import numpy as np

import jax
from jax.sharding import Mesh

from pslite_tpu.parallel.engine import CollectiveEngine
from pslite_tpu.parallel.sparse import SparseEngine


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("kv",))


def test_dense_shrink_then_grow():
    eng = CollectiveEngine(mesh=_mesh(8))
    keys = np.arange(6, dtype=np.uint64)
    val_len = 100  # total=600: padded 600->608 on 8, ->600 on 4
    eng.register_dense("b", keys, val_len)
    rng = np.random.RandomState(0)
    g8 = rng.randn(8, 600).astype(np.float32)
    out = np.asarray(eng.push_pull("b", g8))
    want = g8.sum(0)
    np.testing.assert_allclose(out, want, rtol=1e-5)

    eng.reshard(_mesh(4))
    assert eng.num_shards == 4
    # State survived the recut.
    np.testing.assert_allclose(
        np.asarray(eng.pull("b")), want, rtol=1e-5
    )
    # Continued training on the new fan-in.
    g4 = rng.randn(4, 600).astype(np.float32)
    out = np.asarray(eng.push_pull("b", g4))
    want = want + g4.sum(0)
    np.testing.assert_allclose(out, want, rtol=1e-4)

    eng.reshard(_mesh(8))
    np.testing.assert_allclose(
        np.asarray(eng.pull("b")), want, rtol=1e-4
    )


def test_dense_opt_state_survives():
    eng = CollectiveEngine(mesh=_mesh(8), server_handle="sgd_momentum:0.1,0.9")
    keys = np.arange(4, dtype=np.uint64)
    eng.register_dense("m", keys, 64)
    g = np.ones((8, 256), np.float32)
    a = np.asarray(eng.push_pull("m", g))
    eng.reshard(_mesh(4))
    b = np.asarray(eng.push_pull("m", np.ones((4, 256), np.float32)))

    # Host replay of sgd+momentum (store0=0, mom0=0): step 1 sums 8
    # worker rows of ones, step 2 (after reshard) sums 4.
    store, mom = 0.0, 0.0
    expect = []
    for total in (8.0, 4.0):
        mom = 0.9 * mom + total
        store = store - 0.1 * mom
        expect.append(store)
    np.testing.assert_allclose(a, np.full(256, expect[0]), rtol=1e-5)
    np.testing.assert_allclose(b, np.full(256, expect[1]), rtol=1e-5)

    kind, state = eng.opt_state("m")
    assert kind == "sgd_momentum"
    np.testing.assert_allclose(
        np.asarray(state[0])[:256], mom, rtol=1e-5
    )


def test_dense_adam_step_counter_survives():
    eng = CollectiveEngine(mesh=_mesh(4), server_handle="adam")
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("a", keys, 64)
    eng.push_pull("a", np.ones((4, 128), np.float32))
    eng.reshard(_mesh(2))
    kind, state = eng.opt_state("a")
    assert kind == "adam"
    # step counter: one entry per (new) shard, value preserved.
    assert state[2].shape == (2,)
    np.testing.assert_allclose(np.asarray(state[2]), 1.0)
    eng.push_pull("a", np.ones((2, 128), np.float32))
    _, state = eng.opt_state("a")
    np.testing.assert_allclose(np.asarray(state[2]), 2.0)


def test_sparse_reshard_preserves_rows():
    se = SparseEngine(_mesh(8))
    rows, dim = 37, 8  # deliberately not divisible by either shard count
    init = np.arange(rows * dim, dtype=np.float32).reshape(rows, dim)
    se.register_sparse("t", rows, dim, init=init)
    idx = np.array([0, 5, 17, 36], dtype=np.int32)
    got = np.asarray(se.pull("t", np.broadcast_to(idx, (8, 4))))
    np.testing.assert_allclose(got[0], init[idx], rtol=1e-6)

    se.reshard(_mesh(4))
    assert se.num_shards == 4
    got = np.asarray(se.pull("t", np.broadcast_to(idx, (4, 4))))
    np.testing.assert_allclose(got[0], init[idx], rtol=1e-6)

    # Pushes keep working on the new mesh.
    grads = np.ones((4, 4, dim), np.float32)
    se.push("t", np.broadcast_to(idx, (4, 4)), grads)
    got = np.asarray(se.pull("t", np.broadcast_to(idx, (4, 4))))
    np.testing.assert_allclose(got[0], init[idx] + 4.0, rtol=1e-5)


def test_reshard_rejects_2d_layout():
    import pytest

    from pslite_tpu.parallel.mesh import make_mesh
    from pslite_tpu.utils.logging import CheckError

    mesh = make_mesh((2, 4), ("dp", "kv"))
    eng = CollectiveEngine(mesh=mesh, worker_axis="dp")
    with pytest.raises(CheckError):
        eng.reshard(_mesh(4))


def test_sparse_reshard_carries_adagrad_state():
    """Resharding a table mid-training must recut the Adagrad
    accumulator with the rows: continued training on the new mesh
    matches an uninterrupted single-mesh run."""
    rng = np.random.default_rng(5)
    rows, dim = 19, 4
    init = rng.normal(size=(rows, dim)).astype(np.float32)
    idx8 = rng.integers(0, rows, size=(8, 3)).astype(np.int32)
    g8 = rng.normal(size=(8, 3, dim)).astype(np.float32)
    idx4, g4 = idx8[:4], g8[:4]

    # Reference: stay on the 4-shard mesh the whole time.
    ref = SparseEngine(_mesh(4))
    ref.register_sparse("t", rows, dim, init=init)
    ref.push("t", idx4, g4, handle="row_adagrad:0.1")
    ref.push("t", idx4, g4, handle="row_adagrad:0.1")
    all_idx = np.broadcast_to(np.arange(rows, dtype=np.int32), (4, rows))
    want = np.asarray(ref.pull("t", all_idx))[0]

    # Elastic: first step on 8 shards (same per-row aggregate G: the 4
    # extra workers push zeros), reshard down to 4, second step there.
    se = SparseEngine(_mesh(8))
    se.register_sparse("t", rows, dim, init=init)
    z8 = np.concatenate([g4, np.zeros_like(g4)], axis=0)
    se.push("t", np.concatenate([idx4, idx4], axis=0), z8,
            handle="row_adagrad:0.1")
    se.reshard(_mesh(4))
    se.push("t", idx4, g4, handle="row_adagrad:0.1")
    got = np.asarray(se.pull("t", all_idx))[0]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_dense_reshard_carries_adagrad_state():
    eng = CollectiveEngine(mesh=_mesh(8))
    keys = np.arange(2, dtype=np.uint64)
    init = np.linspace(0, 1, 2 * 64).astype(np.float32)
    eng.register_dense("p", keys, 64, init=init)
    g = np.ones((8, 2 * 64), np.float32)
    eng.push_pull("p", g, handle="adagrad:0.1")
    before = np.asarray(eng.opt_state("p")[1][0])
    eng.reshard(_mesh(4))
    kind, arrs = eng.opt_state("p")
    assert kind == "adagrad" and len(arrs) == 1
    np.testing.assert_allclose(np.asarray(arrs[0])[: 2 * 64],
                               before[: 2 * 64], rtol=1e-6)
    out = np.asarray(eng.push_pull("p", np.ones((4, 2 * 64), np.float32),
                                   handle="adagrad:0.1"))
    assert np.isfinite(out).all()


def test_dense_2d_reshard_preserves_state():
    """A 2-D (worker_axis) engine reshards onto a different 2-D mesh:
    worker fan-in and server-shard count both recut, values preserved."""
    from pslite_tpu.parallel.mesh import make_mesh

    mesh = make_mesh((2, 4), ("dp", "kv"))
    eng = CollectiveEngine(mesh=mesh, worker_axis="dp")
    keys = np.arange(3, dtype=np.uint64)
    eng.register_dense("b2d", keys, 40)  # total 120
    grads = np.tile(np.arange(120, dtype=np.float32), (2, 1))
    out1 = np.asarray(eng.push_pull("b2d", grads))[:120]
    np.testing.assert_allclose(out1, 2 * np.arange(120), rtol=1e-6)

    eng.reshard(make_mesh((4, 2), ("dp", "kv")))
    assert eng.num_workers == 4 and eng.num_shards == 2
    # State survived the recut.
    np.testing.assert_allclose(
        np.asarray(eng.pull("b2d"))[:120], 2 * np.arange(120), rtol=1e-6
    )
    # New fan-in works end to end.
    grads4 = np.tile(np.arange(120, dtype=np.float32), (4, 1))
    out2 = np.asarray(eng.push_pull("b2d", grads4))[:120]
    np.testing.assert_allclose(out2, 6 * np.arange(120), rtol=1e-6)
