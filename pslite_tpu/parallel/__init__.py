"""TPU parallel data plane: device meshes, collective KV engine, sparse
tables, and sequence-parallel primitives."""

from .mesh import default_mesh, make_mesh
from .engine import CollectiveEngine, DenseBucket
from .coalesce import CoalescingDispatcher
from .pipeline import pipeline_apply, pipeline_loss, stack_layers

__all__ = [
    "CoalescingDispatcher",
    "CollectiveEngine",
    "DenseBucket",
    "default_mesh",
    "make_mesh",
    "pipeline_apply",
    "pipeline_loss",
    "stack_layers",
]
