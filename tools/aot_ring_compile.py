"""AOT-compile the fused Pallas ring kernel for real multi-chip TPU
topologies — no chips required.

The bench environment exposes ONE physical chip, and the ring kernel
needs >=2 ring devices — so every real-TPU benchmark number is the XLA
path and the kernel itself had only ever run under the CPU interpreter
(r03 verdict, missing #1).  Mosaic lowering for real hardware is a
different compiler path from the interpreter; this tool exercises it:
``jax.experimental.topologies`` builds an AOT device set for a named
TPU topology, the engine builds its ring programs against a mesh of
those devices, and ``.lower().compile()`` runs the full
Mosaic+XLA pipeline.  Execution stays out of reach without hardware;
compilation does not.

Writes a machine-checkable report to docs/AOT_RING.json (and a human
summary to stdout).  Configs cover every kernel variant the engine can
select: bidirectional f32/bf16, int8 wire compression, push-only,
2-D multi-axis (dp sub-rings + kv gather), the 3-D torus (dp sub-rings
+ two-axis kv gather), and the fused replay scan.

Usage: python tools/aot_ring_compile.py [--topology v5e:2x4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The AOT topology client compiles LOCALLY (libtpu compile-only) — the
# axon tunnel is not needed, and letting the axon backend initialize
# would HANG this tool whenever the tunnel is down.  Pin CPU via the
# shared counter-measure helper (kept in sync with the sitecustomize).
from pslite_tpu.utils.platform_pin import pin_cpu

pin_cpu(1)


def _compile_one(eng, mesh, kind: str, padded: int, dtype, steps: int = 0):
    """Lower + compile one ring program against the AOT mesh; returns a
    result row (mosaic presence, compile seconds, executable size)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = eng.axis
    waxis = eng.worker_axis
    store_spec = NamedSharding(mesh, P(axis))
    if waxis is None:
        grads_spec = NamedSharding(mesh, P(axis, None))
        rows = eng.num_shards
    else:
        grads_spec = NamedSharding(mesh, P(waxis, axis))
        rows = eng.num_workers

    store_sds = jax.ShapeDtypeStruct((padded,), dtype, sharding=store_spec)
    if kind == "replay":
        prog = eng._replay_program(steps, padded, dtype, "_default",
                                   keep="last", stateful=False)
        seq_spec = NamedSharding(mesh, P(None, axis, None))
        args = (store_sds,
                jax.ShapeDtypeStruct((steps, rows, padded), dtype,
                                     sharding=seq_spec))
    elif kind == "push":
        prog = eng._ring_program_op("push", padded, dtype, "_default")
        args = (store_sds,
                jax.ShapeDtypeStruct((rows, padded), dtype,
                                     sharding=grads_spec))
    else:  # push_pull
        prog = eng._ring_program(padded, dtype, "_default")
        args = (store_sds,
                jax.ShapeDtypeStruct((rows, padded), dtype,
                                     sharding=grads_spec))

    t0 = time.perf_counter()
    lowered = prog.lower(*args)
    hlo = lowered.as_text()
    mosaic = "tpu_custom_call" in hlo
    compiled = lowered.compile()
    dt = time.perf_counter() - t0
    return {
        "mosaic_custom_call": mosaic,
        "compile_seconds": round(dt, 1),
        "hlo_bytes": len(hlo),
        "executable_text_bytes": len(compiled.as_text()),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:2x4",
                    help="jax.experimental.topologies name")
    ap.add_argument("--out", default="docs/AOT_RING.json")
    args = ap.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from pslite_tpu.parallel.engine import CollectiveEngine

    report = {
        "topology": args.topology,
        "jax_version": jax.__version__,
        "configs": {},
    }
    try:
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name=args.topology
        )
    except Exception as exc:  # noqa: BLE001 - record the exact blocker
        report["error"] = f"topology unavailable: {exc!r}"
        print(json.dumps(report, indent=1))
        return 1

    devs = np.array(topo.devices)
    n = devs.size
    mesh1 = Mesh(devs.reshape(n), ("kv",))
    eng1 = CollectiveEngine(mesh=mesh1, impl="pallas")
    engc = CollectiveEngine(mesh=mesh1, impl="pallas", wire_compress="int8")
    mesh2 = Mesh(devs.reshape(n // 2, 2), ("dp", "kv"))
    eng2 = CollectiveEngine(mesh=mesh2, impl="pallas", worker_axis="dp")
    mesh3 = Mesh(devs.reshape(2, 2, n // 4), ("dp", "kv1", "kv2"))
    eng3 = CollectiveEngine(mesh=mesh3, axis_name=("kv1", "kv2"),
                            worker_axis="dp", impl="pallas")

    padded = n * 65536  # 2MB f32 per bucket at n=8
    configs = [
        ("push_pull_f32_bidir", eng1, mesh1, "push_pull", padded,
         jnp.float32, 0),
        ("push_pull_bf16", eng1, mesh1, "push_pull", padded,
         jnp.bfloat16, 0),
        ("push_pull_int8_wire", engc, mesh1, "push_pull", padded,
         jnp.float32, 0),
        ("push_only", eng1, mesh1, "push", padded, jnp.float32, 0),
        ("multi_axis_2d", eng2, mesh2, "push_pull", padded,
         jnp.float32, 0),
        ("multi_axis_3d_torus", eng3, mesh3, "push_pull", padded,
         jnp.float32, 0),
        ("replay_scan_T4", eng1, mesh1, "replay", padded, jnp.float32, 4),
    ]
    ok = True
    for name, eng, mesh, kind, plen, dtype, steps in configs:
        impl = eng._effective_impl(dtype, "sum")
        if impl != "pallas":
            report["configs"][name] = {"error": f"gate says {impl}"}
            ok = False
            continue
        try:
            report["configs"][name] = _compile_one(
                eng, mesh, kind, plen, dtype, steps
            )
            if not report["configs"][name]["mosaic_custom_call"]:
                ok = False
        except Exception as exc:  # noqa: BLE001 - record per-config
            report["configs"][name] = {
                "error": f"{type(exc).__name__}: {exc}"[:500]
            }
            ok = False
    report["all_ok"] = ok
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), args.out)
    with open(out, "w") as fh:
        json.dump(report, fh, indent=1)
        fh.write("\n")
    print(json.dumps(report, indent=1))
    return 0 if ok else 2


if __name__ == "__main__":
    sys.exit(main())
