"""SArray — the zero-copy shared-buffer abstraction of the data plane.

Capability parity with the reference's ``include/ps/sarray.h`` (378 L):
ref-counted zero-copy arrays with pointer-copy assignment, reinterpreting
casts between element types (``sarray.h:81-91``), zero-copy ``segment()``
slices (``:294-305``), and device placement tags carried through casts and
slices (``:14-20, 319-323``).

On TPU the host-side representation is a numpy view (numpy's ``base``
ref-counting gives the zero-copy sharing semantics for free); device-side
buffers are ``jax.Array`` shards referenced by handle.  The device tags tell
the van where the bytes live / must land — the ICI van uses them to route
HBM-resident payloads without a host round-trip.
"""

from __future__ import annotations

import enum
from typing import Any, Optional

import numpy as np


class DeviceType(enum.IntEnum):
    """Where a buffer lives (reference: sarray.h device tags UNK/CPU/GPU)."""

    UNK = 0
    CPU = 1
    TPU = 2  # the reference's GPU slot; here: HBM on a TPU chip


class SArray:
    """A typed view over shared bytes, with src/dst device placement tags.

    Copying an SArray never copies data — only the view.  ``segment`` and
    ``astype_view`` return new SArrays aliasing the same buffer, preserving
    device tags (reference: sarray.h:294-305, 319-323).
    """

    __slots__ = (
        "data",
        "src_device",
        "src_device_id",
        "dst_device",
        "dst_device_id",
        "device_array",
    )

    def __init__(
        self,
        data: Any = None,
        dtype: Any = None,
        src_device: DeviceType = DeviceType.UNK,
        src_device_id: int = -1,
        dst_device: DeviceType = DeviceType.UNK,
        dst_device_id: int = -1,
    ):
        if data is None:
            self.data = np.empty(0, dtype=dtype or np.uint8)
        elif isinstance(data, SArray):
            self.data = data.data
            src_device = data.src_device
            src_device_id = data.src_device_id
            dst_device = data.dst_device
            dst_device_id = data.dst_device_id
        elif isinstance(data, np.ndarray):
            self.data = data if dtype is None else data.view(dtype)
        elif isinstance(data, (bytes, bytearray, memoryview)):
            self.data = np.frombuffer(data, dtype=dtype or np.uint8)
        else:
            self.data = np.asarray(data, dtype=dtype)
        self.src_device = src_device
        self.src_device_id = src_device_id
        self.dst_device = dst_device
        self.dst_device_id = dst_device_id
        # Optional handle to an on-device jax.Array this view mirrors.
        self.device_array = None

    # -- zero-copy transforms ------------------------------------------------

    def astype_view(self, dtype) -> "SArray":
        """Reinterpreting cast (no copy) — reference sarray.h:81-91."""
        out = SArray(self.data.view(dtype))
        out._copy_tags(self)
        return out

    def segment(self, begin: int, end: int) -> "SArray":
        """Zero-copy slice [begin, end) — reference sarray.h:294-305."""
        out = SArray(self.data[begin:end])
        out._copy_tags(self)
        return out

    def _copy_tags(self, other: "SArray") -> None:
        self.src_device = other.src_device
        self.src_device_id = other.src_device_id
        self.dst_device = other.dst_device
        self.dst_device_id = other.dst_device_id
        self.device_array = other.device_array

    # -- properties ----------------------------------------------------------

    def __len__(self) -> int:
        return len(self.data)

    @property
    def size(self) -> int:
        return int(self.data.size)

    @property
    def nbytes(self) -> int:
        return int(self.data.nbytes)

    @property
    def dtype(self):
        return self.data.dtype

    def tobytes(self) -> bytes:
        return self.data.tobytes()

    def numpy(self) -> np.ndarray:
        return self.data

    def shares_memory(self, other: "SArray") -> bool:
        return np.shares_memory(self.data, other.data)

    def __getitem__(self, idx):
        return self.data[idx]

    def __repr__(self) -> str:
        return (
            f"SArray(dtype={self.data.dtype}, size={self.data.size}, "
            f"src={self.src_device.name}:{self.src_device_id}, "
            f"dst={self.dst_device.name}:{self.dst_device_id})"
        )


def as_sarray(x: Any, dtype=None) -> SArray:
    return x if isinstance(x, SArray) else SArray(x, dtype=dtype)
