"""Elastic restart across fleet sizes (VERDICT r03 missing #6): a
checkpoint saved by an 8-shard engine restores into a 4-shard engine —
stores, adam state, sparse tables + adagrad accumulators — and training
continues as if uninterrupted.  The end-to-end leg drives the keepalive
launcher (exit 254 -> restart -> smaller fleet) via
examples/elastic_restart.py."""

import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pslite_tpu import checkpoint
from pslite_tpu.parallel import CollectiveEngine, default_mesh
from pslite_tpu.parallel.mesh import make_mesh
from pslite_tpu.parallel.sparse import SparseEngine


def _build(mesh, total=100, rows=13, dim=4):
    eng = CollectiveEngine(mesh=mesh, server_handle="adam:0.01")
    se = SparseEngine(mesh)
    eng.register_dense("w", np.arange(1, dtype=np.uint64), total)
    se.register_sparse("emb", rows, dim)
    return eng, se


def _step(eng, se, step, total=100, rows=13, dim=4):
    W = eng.num_shards
    g = np.random.default_rng(50 + step).normal(size=total).astype(
        np.float32
    )
    eng.push_pull("w", np.tile(g / W, (W, 1)))
    rng = np.random.default_rng(80 + step)
    idx = np.zeros((W, 5), np.int32)
    gr = np.zeros((W, 5, dim), np.float32)
    idx[0] = rng.integers(0, rows, size=5).astype(np.int32)
    gr[0] = rng.normal(size=(5, dim)).astype(np.float32)
    se.push("emb", idx, gr, handle="row_adagrad:0.1,1e-8")
    se.block("emb")


def test_restore_onto_half_fleet_matches_uninterrupted(tmp_path):
    """8-shard save -> 4-shard restore: final state equals a run that
    never restarted.  total=100 makes the shard padding DIFFER between
    the two fleets (104 vs 100), exercising the de-padded v2 layout."""
    mesh8, mesh4 = default_mesh(), make_mesh((4,), ("kv",))

    ref_eng, ref_se = _build(mesh8)
    for s in range(4):
        _step(ref_eng, ref_se, s)
    want = np.asarray(ref_eng.pull("w"))
    want_rows = np.asarray(
        ref_se.pull("emb", np.tile(np.arange(13, dtype=np.int32), (8, 1)))
    )[0]
    want_acc = np.asarray(ref_se.acc_array("emb"))

    eng8, se8 = _build(mesh8)
    for s in range(2):
        _step(eng8, se8, s)
    path = str(tmp_path / "elastic_shrink")
    checkpoint.save_engine(eng8, path, sparse_engine=se8)

    eng4, se4 = _build(mesh4)
    checkpoint.restore_engine(eng4, path, sparse_engine=se4)
    for s in range(2, 4):
        _step(eng4, se4, s)
    np.testing.assert_allclose(np.asarray(eng4.pull("w")), want,
                               rtol=1e-5, atol=1e-5)
    got_rows = np.asarray(
        se4.pull("emb", np.tile(np.arange(13, dtype=np.int32), (4, 1)))
    )[0]
    np.testing.assert_allclose(got_rows, want_rows, rtol=1e-5, atol=1e-5)
    # Accumulator state carried: same global rows on either fleet.
    from pslite_tpu.parallel.sparse import _deinterleave_rows

    acc4 = np.asarray(se4.acc_array("emb"))
    deint = _deinterleave_rows(acc4, 13, se4.table("emb").rows_per_shard,
                               4)
    deint8 = _deinterleave_rows(
        want_acc, 13, ref_se.table("emb").rows_per_shard, 8
    )
    np.testing.assert_allclose(deint, deint8, rtol=1e-5, atol=1e-5)


def test_restore_onto_larger_fleet(tmp_path):
    """The portable layout also grows: 4-shard save -> 8-shard restore."""
    mesh8, mesh4 = default_mesh(), make_mesh((4,), ("kv",))
    eng4, se4 = _build(mesh4)
    for s in range(2):
        _step(eng4, se4, s)
    before = np.asarray(eng4.pull("w"))
    path = str(tmp_path / "elastic_grow")
    checkpoint.save_engine(eng4, path, sparse_engine=se4)

    eng8, se8 = _build(mesh8)
    checkpoint.restore_engine(eng8, path, sparse_engine=se8)
    np.testing.assert_allclose(np.asarray(eng8.pull("w")), before,
                               rtol=1e-6)


@pytest.mark.parametrize("backend", ["npz", "orbax"])
def test_keepalive_restart_into_half_fleet(tmp_path, backend):
    """END-TO-END: examples/elastic_restart.py under the keepalive
    launcher — save at 8 shards, exit 254, restart, restore at 4
    shards, verify against the uninterrupted host recurrence.  Both
    fleet-portable checkpoint backends drive the same loop (the orbax
    one closes r04 weak #7: multi-host-capable saves that restore into
    a different fleet)."""
    if backend == "orbax" and not checkpoint.have_orbax():
        pytest.skip("orbax not installed")
    ck = str(tmp_path / "elastic_ck")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    example = os.path.join(repo_root, "examples", "elastic_restart.py")
    env = dict(os.environ, PS_CKPT=ck, PS_CKPT_BACKEND=backend)
    for var in ("JAX_PLATFORMS", "XLA_FLAGS"):
        env.pop(var, None)
    proc = subprocess.run(
        [
            sys.executable, "-m", "pslite_tpu.tracker.local",
            "-n", "0", "-s", "0", "--", sys.executable, example,
        ],
        capture_output=True,
        timeout=300,
        cwd=repo_root,
        env=env,
    )
    out = proc.stdout.decode()
    assert proc.returncode == 0, (out + proc.stderr.decode())[-2000:]
    assert "saved 2-step checkpoint from 8 shards" in out, out[-1500:]
    assert "ELASTIC_RESTART_OK restored onto 4 shards" in out, out[-1500:]


def test_v1_same_fleet_rps_rounding_compat():
    """A v1-era interleaved table (rows_per_shard = plain ceil(rows/S),
    before lane-pack rounding) restores onto a same-shard-count engine;
    any OTHER interleaved size still fails loud (the shape cannot
    identify the saver's shard count)."""
    from pslite_tpu.parallel.sparse import (
        SparseEngine,
        _interleave_rows,
    )
    from pslite_tpu.utils.logging import CheckError

    rows, dim, S = 13, 4, 8
    mesh8 = default_mesh()
    se = SparseEngine(mesh8)
    se.register_sparse("v1", rows, dim)
    # v1 layout: unrounded rps = ceil(13/8) = 2 (today's is 32).
    glob = np.arange(rows * dim, dtype=np.float32).reshape(rows, dim)
    v1_host = _interleave_rows(glob, rows, 2, S, np.float32)
    assert v1_host.shape == (16, dim)
    se.set_store_array("v1", v1_host)
    got = np.asarray(
        se.pull("v1", np.tile(np.arange(rows, dtype=np.int32), (S, 1)))
    )[0]
    np.testing.assert_allclose(got, glob)

    # An interleaved array of any OTHER size must not be silently
    # re-interpreted.  (A same-SIZE layout from a different fleet —
    # e.g. S=4/rps=4 also giving 16 rows — is inherently
    # indistinguishable by shape; v1 meta carries no shard count.)
    other = _interleave_rows(glob, rows, 5, 4, np.float32)  # 20 rows
    with pytest.raises(CheckError, match="bad restore shape"):
        se.set_store_array("v1", other)
