"""Process-wide configuration lookup.

Equivalent of the reference's ``Environment`` singleton
(``include/ps/internal/env.h:15-63``): values come from OS environment
variables, optionally overridden by an injected dict (used by in-process
multi-node tests, where several logical nodes with different configs share one
OS environment).
"""

from __future__ import annotations

import os
import threading
from typing import Mapping, Optional


class Environment:
    """Env-var lookup with an optional injected override map.

    Unlike the reference's process-global singleton, instances can be created
    per logical node so a single test process can host many nodes; the
    module-level :func:`get` returns the default process-wide instance.
    """

    def __init__(self, overrides: Optional[Mapping[str, str]] = None):
        self._overrides = dict(overrides) if overrides else {}

    def find(self, key: str, default: Optional[str] = None) -> Optional[str]:
        if key in self._overrides:
            return self._overrides[key]
        return os.environ.get(key, default)

    def find_int(self, key: str, default: int = 0) -> int:
        val = self.find(key)
        if val is None or val == "":
            return default
        return int(val)

    def find_float(self, key: str, default: float = 0.0) -> float:
        val = self.find(key)
        if val is None or val == "":
            return default
        return float(val)

    def find_bool(self, key: str, default: bool = False) -> bool:
        val = self.find(key)
        if val is None or val == "":
            return default
        return val.strip().lower() not in ("0", "false", "no", "off")

    def set(self, key: str, value: str) -> None:
        self._overrides[key] = str(value)


_lock = threading.Lock()
_default: Optional[Environment] = None


def get() -> Environment:
    """The process-wide default environment (OS env vars only)."""
    global _default
    with _lock:
        if _default is None:
            _default = Environment()
        return _default


def init_with(overrides: Mapping[str, str]) -> Environment:
    """Replace the process-wide default with one carrying overrides."""
    global _default
    with _lock:
        _default = Environment(overrides)
        return _default
