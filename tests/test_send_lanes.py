"""Per-peer send lanes (van.py) + vectored TCP writes (tcp_van.py).

The lane scheduler replaced the van-wide send lock: sends to different
peers must overlap (one slow peer bounds the fan-out, not the sum of
peers), per-lane dispatch errors park and re-raise on the next send(),
and drain retires every lane before TERMINATE.  TcpVan's pure-Python
send path must put a whole frame on the wire with ONE sendmsg when the
OS accepts the full vector, falling back to sendall on partial writes.
"""

import threading
import time

import numpy as np
import pytest

from pslite_tpu.environment import Environment
from pslite_tpu.message import Message
from pslite_tpu.vans.van import Van


class _StubPo:
    """Just enough Postoffice for a transport-less Van."""

    is_scheduler = False
    is_worker = True

    def __init__(self, env):
        self.env = env

    @staticmethod
    def role_str() -> str:
        return "test"


def _make_van(cls=Van, env=None):
    return cls(_StubPo(Environment(env or {})))


def _data_msg(recver: int, tag: float = 0.0, priority: int = 0) -> Message:
    m = Message()
    m.meta.sender = 1
    m.meta.recver = recver
    m.meta.priority = priority
    m.add_data(np.full(4, tag, np.float32))
    return m


def _wait_until(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


def test_fanout_overlaps_slow_peer():
    """Deterministic overlap proof: while peer 0's send is BLOCKED in
    the transport, sends to peers 1..3 must still complete — impossible
    under the old van-wide send lock."""
    blocker = threading.Event()
    sent = []

    class _GatedVan(Van):
        def send_msg(self, msg):
            if msg.meta.recver == 0:
                assert blocker.wait(timeout=10), "slow peer never released"
            sent.append(msg.meta.recver)
            return msg.meta.data_size

    van = _make_van(_GatedVan)
    try:
        for peer in range(4):  # slow peer first: worst head-of-line case
            van.send(_data_msg(peer))
        assert _wait_until(lambda: {1, 2, 3} <= set(sent))
        assert 0 not in sent  # still blocked — the others overtook it
        blocker.set()
        van._drain_send_lanes(timeout_s=10.0)
        assert sorted(sent) == [0, 1, 2, 3]
    finally:
        blocker.set()
        van._lane_stop = True
        van.profiler.close()


def test_fanout_bounded_by_slow_peer_wall_time():
    """Timing form of the acceptance criterion: N-peer fan-out with one
    slow peer completes in ~slow-peer time, not the serialized sum."""
    slow_s = 0.4

    class _SlowPeerVan(Van):
        def send_msg(self, msg):
            time.sleep(slow_s if msg.meta.recver == 0 else 0.01)
            return msg.meta.data_size

    van = _make_van(_SlowPeerVan)
    try:
        t0 = time.perf_counter()
        for peer in range(4):
            van.send(_data_msg(peer))
        van._drain_send_lanes(timeout_s=30.0)
        wall = time.perf_counter() - t0
        # Serialized cost would be >= 0.43s; grant generous CI slack
        # but stay strictly below the no-overlap regime.
        assert wall < slow_s + 0.25, f"fan-out did not overlap: {wall:.3f}s"
    finally:
        van.profiler.close()


def test_lane_error_parks_and_reraises_on_next_send():
    class _FailingVan(Van):
        def send_msg(self, msg):
            if msg.meta.recver == 7:
                raise OSError("wire down")
            return msg.meta.data_size

    van = _make_van(_FailingVan)
    try:
        van.send(_data_msg(7))
        assert _wait_until(lambda: van._lane_error is not None)
        with pytest.raises(OSError, match="wire down"):
            van.send(_data_msg(8))
        # Read-and-clear: the error surfaces exactly once.
        assert van._lane_error is None
        van.send(_data_msg(8))
        van._drain_send_lanes(timeout_s=10.0)
    finally:
        van.profiler.close()


def test_lanes_disabled_dispatches_inline():
    """PS_SEND_LANES=0: the synchronous regime — send() returns only
    after the transport write, and transport errors raise in place."""
    sent = []

    class _RecordingVan(Van):
        def send_msg(self, msg):
            sent.append((msg.meta.recver, threading.current_thread()))
            return msg.meta.data_size

    van = _make_van(_RecordingVan, env={"PS_SEND_LANES": "0"})
    try:
        van.send(_data_msg(3))
        assert len(sent) == 1 and sent[0][1] is threading.current_thread()
        assert not van._lanes or all(
            lane.thread is None for lane in van._lanes.values()
        )
    finally:
        van.profiler.close()


def test_drain_then_late_send_goes_inline():
    """After drain retires the lanes, a straggler send() must dispatch
    inline rather than stranding in a consumer-less queue."""
    sent = []

    class _RecordingVan(Van):
        def send_msg(self, msg):
            sent.append(msg.meta.recver)
            return msg.meta.data_size

    van = _make_van(_RecordingVan)
    try:
        van.send(_data_msg(2))
        van._drain_send_lanes(timeout_s=10.0)
        assert sent == [2]
        van.send(_data_msg(4))  # post-drain: inline path
        assert sent == [2, 4]
    finally:
        van.profiler.close()


def test_retransmit_rides_owning_lane():
    """send_msg_locked (the resender's retransmit entry) must neither
    re-assign sids nor re-buffer, and must flow through the peer's lane
    when lanes are live."""
    seen = []

    class _RecordingVan(Van):
        def send_msg(self, msg):
            seen.append((msg.meta.recver, msg.meta.sid))
            return msg.meta.data_size

    van = _make_van(_RecordingVan)
    try:
        msg = _data_msg(5)
        van.send(msg)
        van._drain_send_lanes(timeout_s=10.0)
        van._lane_stop = False  # re-arm (as start() would)
        sid_after_first = dict(van._send_sids)
        van.send_msg_locked(msg)  # retransmit of the SAME message
        van._drain_send_lanes(timeout_s=10.0)
        assert seen == [(5, 0), (5, 0)]  # same sid on the wire twice
        assert van._send_sids == sid_after_first  # no sid re-assignment
    finally:
        van.profiler.close()


# -- TcpVan vectored writes ----------------------------------------------


class _FakeSock:
    """Socket double recording send calls; optionally accepts only
    ``first_accept`` bytes of the first sendmsg (partial-write path)."""

    def __init__(self, first_accept=None):
        self.first_accept = first_accept
        self.sendmsg_calls = 0
        self.sendall_calls = 0
        self.wire = bytearray()

    def sendmsg(self, views):
        self.sendmsg_calls += 1
        total = sum(v.nbytes for v in views)
        accept = total
        if self.sendmsg_calls == 1 and self.first_accept is not None:
            accept = min(self.first_accept, total)
        remaining = accept
        for v in views:
            take = min(remaining, v.nbytes)
            self.wire += v[:take]
            remaining -= take
            if remaining == 0:
                break
        return accept

    def sendall(self, v):
        self.sendall_calls += 1
        self.wire += v


class _NoVectorSock(_FakeSock):
    sendmsg = None  # transports without scatter-gather support


def _tcp_van():
    from pslite_tpu.vans.tcp_van import TcpVan

    return _make_van(TcpVan, env={"PS_NATIVE": "0"})


def _frame_bytes(msg) -> bytes:
    from pslite_tpu import wire

    return b"".join(wire.pack_frame(msg))


@pytest.mark.parametrize("n_segs", [0, 1, 3])
def test_tcp_one_sendmsg_per_message(n_segs):
    van = _tcp_van()
    try:
        sock = _FakeSock()
        van._send_socks[9] = sock
        msg = _data_msg(9)
        msg.data, msg.meta.data_type, msg.meta.data_size = [], [], 0
        for i in range(n_segs):
            msg.add_data(np.arange(16 + i, dtype=np.float32))
        want = _frame_bytes(msg)
        nbytes = van.send_msg(msg)
        assert nbytes == len(want)
        assert bytes(sock.wire) == want
        # The whole [header, lens, meta, *data] vector in ONE syscall.
        assert sock.sendmsg_calls == 1 and sock.sendall_calls == 0
        assert van._send_syscalls == 1
    finally:
        van.profiler.close()


def test_tcp_partial_sendmsg_falls_back_to_sendall():
    van = _tcp_van()
    try:
        sock = _FakeSock(first_accept=11)  # mid-chunk cut
        van._send_socks[9] = sock
        msg = _data_msg(9, tag=3.0)
        msg.add_data(np.arange(32, dtype=np.float32))
        want = _frame_bytes(msg)
        assert van.send_msg(msg) == len(want)
        assert bytes(sock.wire) == want  # byte-exact despite the cut
        assert sock.sendmsg_calls == 1 and sock.sendall_calls >= 1
    finally:
        van.profiler.close()


def test_tcp_sendall_fallback_without_sendmsg():
    van = _tcp_van()
    try:
        sock = _NoVectorSock()
        van._send_socks[9] = sock
        msg = _data_msg(9, tag=5.0)
        want = _frame_bytes(msg)
        assert van.send_msg(msg) == len(want)
        assert bytes(sock.wire) == want
        assert sock.sendall_calls >= 1
    finally:
        van.profiler.close()
