"""Minimal XPlane (.xplane.pb) reader: on-device busy time extraction.

``jax.profiler.trace`` (wrapped by :class:`profiling.device_trace`) dumps
an XSpace protobuf per host.  Wall-clock benchmarking through the axon
tunnel is untrustworthy — r02 measured a "goodput" above the chip's
physical HBM bandwidth because the tunnel elides/pipelines device work —
so the honest denominator is the DEVICE-side timeline: the union of XLA
op intervals on the TPU planes.  This module parses exactly the fields
needed (wire-format protobuf, no protobuf/tensorflow dependency):

    XSpace { repeated XPlane planes = 1; }
    XPlane { int64 id=1; string name=2; repeated XLine lines=3; }
    XLine  { int64 id=1; string name=2; int64 timestamp_ns=3;
             repeated XEvent events=4; }
    XEvent { int64 metadata_id=1; int64 offset_ps=2; int64 duration_ps=3; }

(Field numbers from tsl/profiler/protobuf/xplane.proto; unknown fields
are skipped by wire type, so schema growth is tolerated.)

Busy time is computed as the union of [offset, offset+duration] intervals
per line, then the union across a plane's lines is NOT taken — parallel
lines (different cores / queues) are summed, matching "device-seconds of
work" rather than span.  For single-core single-queue runs the two
definitions coincide.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Tuple


def _varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: memoryview) -> Iterator[Tuple[int, int, object]]:
    """(field_number, wire_type, value) over one message's bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _varint(buf, pos)
        elif wt == 1:
            val = buf[pos : pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _varint(buf, pos)
            val = buf[pos : pos + ln]
            pos += ln
        elif wt == 5:
            val = buf[pos : pos + 4]
            pos += 4
        else:  # groups (3/4): not produced by xplane
            raise ValueError(f"unsupported wire type {wt}")
        yield fnum, wt, val


def _line_busy_ps(line_buf: memoryview) -> Tuple[str, int]:
    """(line_name, busy_ps) — busy = union of event intervals."""
    name = ""
    intervals: List[Tuple[int, int]] = []
    for fnum, wt, val in _fields(line_buf):
        if fnum == 2 and wt == 2:
            name = bytes(val).decode("utf-8", "replace")
        elif fnum == 4 and wt == 2:
            off = dur = 0
            for efn, ewt, ev in _fields(val):
                if efn == 2 and ewt == 0:
                    off = ev
                elif efn == 3 and ewt == 0:
                    dur = ev
            if dur > 0:
                intervals.append((off, off + dur))
    if not intervals:
        return name, 0
    intervals.sort()
    busy = 0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            busy += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    busy += cur_e - cur_s
    return name, busy


def plane_busy_ps(path: str) -> Dict[str, Dict[str, int]]:
    """{plane_name: {line_name: busy_ps}} for one .xplane.pb file."""
    with open(path, "rb") as fh:
        space = memoryview(fh.read())
    out: Dict[str, Dict[str, int]] = {}
    for fnum, wt, plane in _fields(space):
        if fnum != 1 or wt != 2:
            continue
        pname = ""
        lines: Dict[str, int] = {}
        for pfn, pwt, val in _fields(plane):
            if pfn == 2 and pwt == 2:
                pname = bytes(val).decode("utf-8", "replace")
            elif pfn == 3 and pwt == 2:
                lname, busy = _line_busy_ps(val)
                if busy:
                    lines[lname] = lines.get(lname, 0) + busy
        if lines:
            out[pname] = lines
    return out


def _line_op_ps(line_buf: memoryview) -> Tuple[str, Dict[int, int]]:
    """(line_name, {metadata_id: summed duration_ps}) for one XLine."""
    name = ""
    per_md: Dict[int, int] = {}
    for fnum, wt, val in _fields(line_buf):
        if fnum == 2 and wt == 2:
            name = bytes(val).decode("utf-8", "replace")
        elif fnum == 4 and wt == 2:
            md = dur = 0
            for efn, ewt, ev in _fields(val):
                if efn == 1 and ewt == 0:
                    md = ev
                elif efn == 3 and ewt == 0:
                    dur = ev
            if dur > 0:
                per_md[md] = per_md.get(md, 0) + dur
    return name, per_md


def plane_op_ps(path: str) -> Dict[str, Dict[str, int]]:
    """{plane_name: {op_name: total duration_ps}} over "XLA Ops" lines.

    Op names come from the plane's event_metadata map (XPlane field 4:
    map<int64, XEventMetadata>, XEventMetadata{id=1, name=2}).  Durations
    are SUMMED per op (not interval-unioned): the per-op split is a
    where-does-the-time-go diagnostic, so overlap within one op name is
    attributed to it in full.
    """
    with open(path, "rb") as fh:
        space = memoryview(fh.read())
    out: Dict[str, Dict[str, int]] = {}
    for fnum, wt, plane in _fields(space):
        if fnum != 1 or wt != 2:
            continue
        pname = ""
        md_names: Dict[int, str] = {}
        op_lines: List[Dict[int, int]] = []
        for pfn, pwt, val in _fields(plane):
            if pfn == 2 and pwt == 2:
                pname = bytes(val).decode("utf-8", "replace")
            elif pfn == 3 and pwt == 2:
                lname, per_md = _line_op_ps(val)
                if lname == "XLA Ops" and per_md:
                    op_lines.append(per_md)
            elif pfn == 4 and pwt == 2:
                mid = 0
                mname = ""
                for mfn, mwt, mv in _fields(val):
                    if mfn == 1 and mwt == 0:
                        mid = mv
                    elif mfn == 2 and mwt == 2:
                        for efn, ewt, ev in _fields(mv):
                            if efn == 2 and ewt == 2:
                                mname = bytes(ev).decode("utf-8", "replace")
                md_names[mid] = mname
        if not op_lines:
            continue
        ops: Dict[str, int] = {}
        for per_md in op_lines:
            for mid, ps in per_md.items():
                nm = md_names.get(mid, f"metadata_{mid}")
                ops[nm] = ops.get(nm, 0) + ps
        out[pname] = ops
    return out


def device_op_seconds(logdir: str) -> Dict[str, float]:
    """{op_name: device-seconds} summed over all TPU planes in a trace
    dir — the op-level complement of :func:`device_busy_seconds`."""
    totals: Dict[str, float] = {}
    for path in find_xplane_files(logdir):
        for pname, ops in plane_op_ps(path).items():
            if "TPU" not in pname or "SparseCore" in pname:
                continue
            for nm, ps in ops.items():
                totals[nm] = totals.get(nm, 0.0) + ps / 1e12
    return totals


def find_xplane_files(logdir: str) -> List[str]:
    hits = []
    for root, _dirs, files in os.walk(logdir):
        for f in files:
            if f.endswith(".xplane.pb"):
                hits.append(os.path.join(root, f))
    return sorted(hits)


def device_busy_seconds(logdir: str) -> Dict[str, float]:
    """Per-device-plane busy seconds summed over that plane's op lines.

    Planes whose name contains "TPU" (e.g. ``/device:TPU:0``) are the
    accelerator timelines; ``/host:CPU`` planes carry runtime threads and
    are excluded.  Within a TPU plane, ONLY the "XLA Ops" line(s) carry
    executed kernels — every other line ("Steps", "XLA Modules",
    "#"-prefixed derived lines, future additions) aggregates or annotates
    those same intervals and would double-count them, so the filter is an
    allowlist, not a denylist.
    """
    totals: Dict[str, float] = {}
    for path in find_xplane_files(logdir):
        for pname, lines in plane_busy_ps(path).items():
            if "TPU" not in pname or "SparseCore" in pname:
                continue
            busy = 0
            for lname, ps in lines.items():
                if lname != "XLA Ops":
                    continue
                busy += ps
            if busy:
                totals[pname] = totals.get(pname, 0.0) + busy / 1e12
    return totals
