"""DLRM-style recommender: sparse embedding + dense MLP through the PS.

The reference's sparse workload (1M-key skewed embedding push/pull,
BASELINE config 5) in model form: categorical features look up rows of a
mesh-sharded embedding table (SparseEngine — expert/table parallelism),
dense features feed an MLP whose parameters live in a dense PS store.
One training step does BOTH PS cycles:

- dense params: pull = all_gather, push = psum_scatter (dp axis)
- embedding rows: pull = sparse gather routing, push = scatter-add of the
  per-row gradients into the owning shards

i.e. the hybrid dense+sparse traffic pattern BytePS serves in production.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class DLRMConfig:
    num_rows: int = 1024  # embedding table rows (1M in the benchmark)
    emb_dim: int = 16
    num_cat: int = 4  # categorical features per example
    num_dense: int = 8  # dense features per example
    hidden: int = 64
    dtype: str = "float32"


def init_mlp(rng, cfg: DLRMConfig):
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(cfg.dtype)
    d_in = cfg.num_dense + cfg.num_cat * cfg.emb_dim
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "w1": (jax.random.normal(k1, (d_in, cfg.hidden)) * d_in ** -0.5
               ).astype(dt),
        "b1": jnp.zeros((cfg.hidden,), dt),
        "w2": (jax.random.normal(k2, (cfg.hidden, 1)) * cfg.hidden ** -0.5
               ).astype(dt),
        "b2": jnp.zeros((1,), dt),
    }


def predict(mlp, emb_rows, dense_feats, cfg: DLRMConfig):
    """emb_rows [B, num_cat, emb_dim]; dense [B, num_dense] -> logits [B]."""
    import jax
    import jax.numpy as jnp

    B = dense_feats.shape[0]
    x = jnp.concatenate(
        [dense_feats, emb_rows.reshape(B, -1)], axis=-1
    )
    h = jax.nn.relu(x @ mlp["w1"] + mlp["b1"])
    return (h @ mlp["w2"] + mlp["b2"])[:, 0]


def make_train_step(cfg: DLRMConfig, engine, sparse_engine, lr: float = 0.1,
                    seed: int = 0, emb_optimizer: str = None):
    """Returns ``step(idx, dense, labels) -> loss`` driving both PS planes.

    ``idx``: [W, B, num_cat] rows per worker shard; ``dense``:
    [W, B, num_dense]; ``labels``: [W, B] in {0,1}.

    ``emb_optimizer="row_adagrad"`` trains the embedding table with the
    fused row-wise Adagrad handle (the industry-standard sparse
    optimizer) instead of plain SGD scatter-add.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.flatten_util import ravel_pytree

    from ..utils import logging as log

    log.check(emb_optimizer in (None, "row_adagrad"),
              f"unknown emb_optimizer {emb_optimizer!r}")
    W = engine.num_shards
    mlp0 = init_mlp(jax.random.PRNGKey(seed), cfg)
    flat0, unravel = ravel_pytree(mlp0)

    engine.register_dense("dlrm_mlp", np.arange(1, dtype=np.uint64),
                          flat0.shape[0], init=np.asarray(flat0))
    sparse_engine.register_sparse("dlrm_emb", cfg.num_rows, cfg.emb_dim)

    @jax.jit
    def _grads(flat_mlp, emb_rows, dense, labels):
        def loss_of(flat, rows):
            mlp = unravel(flat)
            logits = predict(mlp, rows, dense.reshape(-1, cfg.num_dense),
                             cfg)
            lbl = labels.reshape(-1).astype(logits.dtype)
            # Sigmoid cross-entropy (CTR-style binary objective).
            return jnp.mean(
                jnp.maximum(logits, 0) - logits * lbl
                + jnp.log1p(jnp.exp(-jnp.abs(logits)))
            )

        (loss, (g_flat, g_rows)) = jax.value_and_grad(
            lambda f, r: loss_of(f, r), argnums=(0, 1)
        )(flat_mlp, emb_rows)
        return loss, g_flat, g_rows

    def step(idx, dense, labels):
        B = idx.shape[1]
        # -- sparse pull: rows for every worker's batch ---------------------
        flat_idx = idx.reshape(W, B * cfg.num_cat)
        rows = sparse_engine.pull("dlrm_emb", flat_idx)  # [W, B*num_cat, d]
        rows = rows.reshape(W * B, cfg.num_cat, cfg.emb_dim)
        # -- dense pull -----------------------------------------------------
        flat_mlp = engine.pull("dlrm_mlp")
        # -- local compute (host-driven across the worker dim) --------------
        loss, g_flat, g_rows = _grads(
            flat_mlp,
            rows,
            jnp.asarray(dense),
            jnp.asarray(labels),
        )
        # -- dense push: aggregated MLP gradient, SGD on shards -------------
        # g_flat already averages over every worker's examples; the push
        # broadcast + psum multiplies by W, so pre-divide.  Pin the
        # accumulate semantics regardless of the engine's default handle.
        engine.push("dlrm_mlp", -lr * g_flat / W, handle="sum")
        # -- sparse push: per-row gradients scatter-add into the table ------
        g_rows = g_rows.reshape(W, B * cfg.num_cat, cfg.emb_dim)
        if emb_optimizer == "row_adagrad":
            # Raw gradient: the fused handle applies -lr*G/(sqrt(acc)+eps).
            sparse_engine.push("dlrm_emb", flat_idx, g_rows,
                               handle=f"row_adagrad:{lr}")
        else:
            sparse_engine.push("dlrm_emb", flat_idx, -lr * g_rows)
        return loss

    return step


def embedding_row(cfg: DLRMConfig, row: int):
    """Deterministic embedding-row values (bit-exact serving checks)."""
    import numpy as np

    base = np.arange(cfg.emb_dim, dtype=np.float32)
    return base * 1e-3 + np.float32(row) + 0.5


def spread_row_keys(cfg: DLRMConfig):
    """Row -> PS key mapping that SPREADS the table uniformly across
    the u64 key space (and therefore across every server's key range).
    Plain ``np.arange`` keys all land on server 0 of a multi-server
    cluster — fine for single-server serving benches, useless for the
    fan-in path, whose whole point is one request touching many
    servers (docs/batching.md, serving fan-in)."""
    import numpy as np

    stride = (1 << 64) // cfg.num_rows
    return (np.arange(cfg.num_rows, dtype=np.uint64)
            * np.uint64(stride))


def push_embedding_table(worker, cfg: DLRMConfig, tenant=None,
                         spread: bool = False) -> None:
    """Publish the full (deterministic) embedding table into the
    message-path PS store — one key per row, ``emb_dim`` floats each.
    The serving-path setup step (docs/qos.md): inference workers then
    pull rows by key.  ``spread=True`` uses :func:`spread_row_keys`
    so the table shards across every server of the cluster."""
    import numpy as np

    keys = (spread_row_keys(cfg) if spread
            else np.arange(cfg.num_rows, dtype=np.uint64))
    vals = np.concatenate(
        [embedding_row(cfg, r) for r in range(cfg.num_rows)]
    )
    worker.wait(worker.push(keys, vals, tenant=tenant))


def serve_fanout_storm(worker, cfg: DLRMConfig, n_reqs: int,
                       fanout: int = 64, seed: int = 0, tenant=None,
                       check_every: int = 32):
    """The DLRM serving FAN-OUT path (docs/batching.md): each request
    is ``fanout`` independent single-row embedding lookups with
    Zipf-distributed rows, issued through ``KVWorker.multi_get`` over
    the SPREAD key layout (:func:`spread_row_keys`) so one request
    touches every server.  Returns per-request wall latencies
    (seconds).  Every ``check_every``-th request is verified bit-exact
    against :func:`embedding_row`."""
    import time

    import numpy as np

    from ..utils import logging as log

    row_keys = spread_row_keys(cfg)
    all_rows = serving_keys(cfg, n_reqs * fanout, seed)
    outs = [np.zeros(cfg.emb_dim, np.float32) for _ in range(fanout)]
    lats = []
    for i in range(n_reqs):
        rows = all_rows[i * fanout:(i + 1) * fanout]
        key_lists = [row_keys[int(r):int(r) + 1] for r in rows]
        t0 = time.perf_counter()
        handle = worker.multi_get(key_lists, outs=outs, tenant=tenant)
        handle.wait()
        lats.append(time.perf_counter() - t0)
        if check_every and i % check_every == 0:
            for j, r in enumerate(rows):
                log.check(
                    np.array_equal(outs[j], embedding_row(cfg, int(r))),
                    f"fan-out pull of row {r} returned wrong values",
                )
    return lats


def serving_keys(cfg: DLRMConfig, n: int, seed: int = 0):
    """Zipf(1.5)-distributed row ids — the inference request stream
    (same skew as ``toy_batch``; the head of this curve is what the
    hot-key cache exists for)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    idx = rng.zipf(1.5, size=n).astype(np.int64)
    return ((idx - 1) % cfg.num_rows).astype(np.uint64)


def serve_embedding_storm(worker, cfg: DLRMConfig, n_pulls: int,
                          seed: int = 0, tenant=None, priority: int = 0,
                          check_every: int = 64):
    """The DLRM serving path over the message-path PS: ``n_pulls``
    single-row embedding pulls with Zipf-distributed keys, returning
    per-pull wall latencies (seconds).  With ``PS_HOT_CACHE=1`` the
    head of the Zipf curve stops paying the round trip (kv/hot_cache.py
    — the pull answers locally when the cached row is stamp-fresh).

    Every ``check_every``-th pull is verified bit-exact against
    :func:`embedding_row` — a cache serving stale or corrupt rows fails
    loudly, not silently."""
    import time

    import numpy as np

    from ..utils import logging as log

    keys = serving_keys(cfg, n_pulls, seed)
    out = np.zeros(cfg.emb_dim, np.float32)
    lats = []
    for i, row in enumerate(keys):
        kk = np.array([row], dtype=np.uint64)
        t0 = time.perf_counter()
        worker.wait(worker.pull(kk, out, priority=priority,
                                tenant=tenant))
        lats.append(time.perf_counter() - t0)
        if check_every and i % check_every == 0:
            log.check(
                np.array_equal(out, embedding_row(cfg, int(row))),
                f"serving pull of row {row} returned wrong values",
            )
    return lats


def toy_batch(cfg: DLRMConfig, workers: int, batch: int, seed: int = 0):
    """Learnable toy CTR data: label correlates with one hot row's use."""
    import numpy as np

    rng = np.random.default_rng(seed)
    idx = rng.zipf(1.5, size=(workers, batch, cfg.num_cat)).astype(np.int64)
    idx = (idx - 1) % cfg.num_rows
    dense = rng.normal(size=(workers, batch, cfg.num_dense)).astype(
        np.float32
    )
    labels = ((idx[..., 0] % 2) ^ (dense[..., 0] > 0)).astype(np.int32)
    return idx.astype(np.int32), dense, labels
