"""XPlane op-level breakdown of an engine benchmark loop.

The round-3 verdict's #1 ask: the device-time headline sits at 58% of the
chip's measured HBM triad peak, and nothing in the repo says where the
other 40% goes.  This tool runs a configurable push_pull loop under
``jax.profiler.trace`` and prints per-XLA-op device-seconds (via
``utils.xplane.device_op_seconds``), plus the implied HBM traffic at the
measured triad rate, so pad/slice/copy parasites show up by name.

Usage:
    python tools/profile_ops.py [--keys 40] [--mb 1] [--iters 30]
                                [--mode push_pull|replay] [--steps 64]
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=40)
    ap.add_argument("--mb", type=float, default=None,
                help="payload MB (default: 1.0; datascatter: 30.72)")
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--mode", default="push_pull",
                    choices=("push_pull", "replay", "datascatter"))
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--handle", default=None)
    ap.add_argument("--zero-copy", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pslite_tpu.parallel.engine import CollectiveEngine

    eng = CollectiveEngine()

    if args.mode == "datascatter":
        # The stress datascatter workload (stress.py run_pattern), op by
        # op: r04 verdict weak #6 — 46 GB/s vs 221-329 for its siblings,
        # attributed to fused gather+scatter-add compute but never
        # substantiated.  Mirror the exact stress geometry (default
        # 30.72 MB: rows = bytes/4/128) so the breakdown names where
        # the device time goes.
        from pslite_tpu.parallel.sparse import SparseEngine

        se = SparseEngine(eng.mesh, eng.axis)
        size_bytes = (int(args.mb * (1 << 20)) if args.mb is not None
                      else 30_720_000)
        dim = 128
        W = eng.num_shards
        rows = max(size_bytes // 4 // dim, W)
        se.register_sparse("prof_tbl", rows, dim)
        batch = max(rows // W, 1)
        idx = np.random.default_rng(0).integers(
            0, rows, size=(W, batch)
        ).astype(np.int32)
        grads = np.ones((W, batch, dim), np.float32)
        se.push("prof_tbl", idx, grads)  # warm
        se.block("prof_tbl")

        def run():
            for _ in range(args.iters):
                se.push("prof_tbl", idx, grads)
            se.block("prof_tbl")

        payload = 4 * W * batch * dim
        moved = payload * args.iters
        _profile(args, payload, moved, run)
        return

    val_len = int((args.mb if args.mb is not None else 1.0)
                  * (1 << 20)) // 4
    keys = np.arange(args.keys, dtype=np.uint64)
    eng.register_dense("prof", keys, val_len)
    bucket = eng.bucket("prof")
    payload = bucket.total_len * 4

    if args.mode == "push_pull":
        if eng.flat_ring_eligible(bucket.dtype, args.handle):
            # Flat [W*padded]: the 1-D ring programs' native grads
            # layout — avoids a per-call relayout in the traced loop.
            inp = jax.device_put(
                jnp.ones((eng.num_shards * bucket.padded_len,),
                         bucket.dtype),
                NamedSharding(eng.mesh, P(eng.axis)),
            )
        else:
            inp = jax.device_put(
                jnp.ones((eng.num_shards, bucket.padded_len),
                         bucket.dtype),
                NamedSharding(eng.mesh, P(eng.axis, None)),
            )
        for _ in range(3):
            out = eng.push_pull("prof", inp, handle=args.handle,
                                zero_copy=args.zero_copy)
        out.block_until_ready()

        def run():
            for _ in range(args.iters):
                out = eng.push_pull("prof", inp, handle=args.handle,
                                    zero_copy=args.zero_copy)
            out.block_until_ready()

        moved = 2 * payload * args.iters
    else:
        seq = np.ones((args.steps, bucket.total_len), np.float32)
        eng.replay("prof", seq, handle=args.handle, keep="last",
                   zero_copy=args.zero_copy).block_until_ready()

        def run():
            eng.replay("prof", seq, handle=args.handle, keep="last",
                       zero_copy=args.zero_copy).block_until_ready()

        moved = 2 * payload * args.steps

    _profile(args, payload, moved, run)


def _profile(args, payload: int, moved: int, run) -> None:
    """Trace ``run`` and print the per-XLA-op device-time breakdown."""
    from pslite_tpu.utils import xplane
    from pslite_tpu.utils.profiling import device_trace

    d = tempfile.mkdtemp(prefix="psprof_")
    try:
        t0 = time.perf_counter()
        with device_trace(d):
            run()
        wall = time.perf_counter() - t0
        ops = xplane.device_op_seconds(d)
        busy = xplane.device_busy_seconds(d)
    finally:
        shutil.rmtree(d, ignore_errors=True)

    if not busy:
        print(f"wall {wall * 1e3:.1f} ms — no TPU plane in the trace "
              f"(CPU-only backend or profiler failure); op breakdown "
              f"needs a TPU timeline")
        return
    total_busy = sum(busy.values()) / max(len(busy), 1)
    print(f"wall {wall * 1e3:.1f} ms   device busy {total_busy * 1e3:.1f} ms"
          f"   goodput {moved / total_busy / 1e9:.1f} GB/s (device)"
          f" / {moved / wall / 1e9:.1f} GB/s (wall)")
    print(f"payload/iter {payload / 1e6:.1f} MB; ops by device time:")
    for nm, s in sorted(ops.items(), key=lambda kv: -kv[1]):
        if s < total_busy * 0.002:
            continue
        print(f"  {s * 1e3:9.3f} ms  {100 * s / total_busy:5.1f}%  {nm}")


if __name__ == "__main__":
    main()
