"""SSH multi-host launcher.

Equivalent of the reference's ``tracker/dmlc_ssh.py``: starts the scheduler
locally and remote workers/servers over ssh, passing the DMLC_* environment
on the remote command line.  Hosts come from a file (one per line, workers
first) or --hosts.

Usage::

    python -m pslite_tpu.tracker.ssh -n 2 -s 2 -H hosts.txt -- \
        python my_app.py
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
from typing import Dict, List

from .local import build_env


def _remote_cmd(env: Dict[str, str], cmd: List[str]) -> str:
    keys = [k for k in env if k.startswith(("DMLC_", "PS_", "BYTEPS_"))]
    exports = " ".join(f"{k}={shlex.quote(env[k])}" for k in sorted(keys))
    return f"env {exports} {' '.join(shlex.quote(c) for c in cmd)}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, required=True)
    ap.add_argument("-H", "--hostfile", required=True)
    ap.add_argument("--root-port", type=int, default=9091)
    ap.add_argument("--van", default="tcp")
    ap.add_argument("--ssh-opts", default="-o StrictHostKeyChecking=no")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    cmd = [c for c in args.cmd if c != "--"]
    if not cmd:
        ap.error("no command given")

    with open(args.hostfile) as fh:
        hosts = [h.strip() for h in fh if h.strip()]
    needed = args.num_workers + args.num_servers
    if len(hosts) < needed:
        # Round-robin hosts when fewer machines than roles.
        hosts = [hosts[i % len(hosts)] for i in range(needed)]

    import socket

    root_uri = socket.gethostbyname(socket.gethostname())
    procs = []

    def launch(host: str, role: str) -> None:
        env = build_env(role, args.num_workers, args.num_servers, root_uri,
                        args.root_port, args.van)
        remote = _remote_cmd(env, cmd)
        if role == "scheduler":
            procs.append(subprocess.Popen(remote, shell=True))
        else:
            procs.append(
                subprocess.Popen(
                    ["ssh"] + args.ssh_opts.split() + [host, remote]
                )
            )

    launch("localhost", "scheduler")
    for i in range(args.num_servers):
        launch(hosts[args.num_workers + i], "server")
    for i in range(args.num_workers):
        launch(hosts[i], "worker")

    rc = 0
    for p in procs:
        rc = rc or p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
