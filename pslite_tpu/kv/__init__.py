from .hot_cache import HotKeyCache
from .kv_app import (KVMeta, KVPairs, KVServer, KVServerDefaultHandle,
                     KVServerOptimizerHandle, KVWorker, OverloadError)
from .simple_app import SimpleApp, SimpleData

__all__ = [
    "HotKeyCache",
    "KVMeta",
    "KVPairs",
    "KVServer",
    "KVServerDefaultHandle",
    "KVServerOptimizerHandle",
    "KVWorker",
    "OverloadError",
    "SimpleApp",
    "SimpleData",
]
