"""Flagship model: forward shapes, and the PS-integrated SPMD training step
on a (dp=4, sp=2) virtual mesh — loss must decrease on learnable toy data."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from pslite_tpu.models.train import make_ps_train_step, toy_batch
from pslite_tpu.models.transformer import ModelConfig, forward, init_params
from pslite_tpu.parallel.mesh import make_mesh


def test_forward_shapes_single_device():
    cfg = ModelConfig(vocab=64, dim=32, heads=2, layers=1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, 64)
    assert np.isfinite(np.asarray(logits)).all()


def test_ps_train_step_loss_decreases():
    cfg = ModelConfig(vocab=32, dim=32, heads=2, layers=1)
    mesh = make_mesh((4, 2), ("dp", "sp"))
    step, store, tok_sharding, _ = make_ps_train_step(cfg, mesh, lr=0.5)

    inputs, targets = toy_batch(cfg, batch=8, seq=16)
    inputs = jax.device_put(inputs, tok_sharding)
    targets = jax.device_put(targets, tok_sharding)

    losses = []
    for _ in range(10):
        store, loss = step(store, inputs, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.9, losses


def test_train_step_ulysses_strategy():
    """sp_strategy='ulysses' trains to the same kind of loss as ring (same
    sharded layout, interchangeable attention)."""
    import jax
    import numpy as np

    from pslite_tpu.models.train import make_ps_train_step, toy_batch
    from pslite_tpu.models.transformer import ModelConfig
    from pslite_tpu.parallel.mesh import make_mesh

    cfg = ModelConfig(vocab=64, dim=32, heads=4, layers=1)
    mesh = make_mesh((2, 4), ("dp", "sp"))
    losses = {}
    for strategy in ("ring", "ulysses"):
        step, store, tok_sharding, _ = make_ps_train_step(
            cfg, mesh, lr=0.1, sp_strategy=strategy
        )
        inputs, targets = toy_batch(cfg, batch=2, seq=32)
        inputs = jax.device_put(inputs, tok_sharding)
        targets = jax.device_put(targets, tok_sharding)
        store, loss = step(store, inputs, targets)
        losses[strategy] = float(loss)
        assert np.isfinite(losses[strategy])
    # Same math, different communication schedule: losses must agree.
    np.testing.assert_allclose(losses["ring"], losses["ulysses"],
                               rtol=1e-4, atol=1e-5)


def test_quantized_transport_convergence_guard():
    """Acceptance (docs/compression.md): training over the loopback
    message-path cluster with ``fp8_e4m3`` + error feedback reaches a
    final loss within 2% of the uncompressed run.  The same run with
    EF disabled is recorded alongside it, documenting the gap in this
    regime (on this fully-converging toy both land close — the
    mechanism-level gap EF closes, persistent quantization bias, is
    pinned deterministically by
    ``tests/test_ops.py::test_error_feedback_removes_quantization_bias``).
    One worker, deterministic data/seeds — the runs differ only in the
    wire codec, so the comparison is reproducible bit-for-bit."""
    import sys

    sys.path.insert(0, "tests")
    from helpers import LoopbackCluster

    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker)
    from pslite_tpu.models.train import kv_train_loop

    cfg = ModelConfig(vocab=32, dim=32, heads=2, layers=1)

    def run(codec, ef):
        cluster = LoopbackCluster(
            num_workers=1, num_servers=1,
            env_extra={"PS_CODEC_EF": "1" if ef else "0"},
        )
        cluster.start()
        servers = []
        try:
            srv = KVServer(0, postoffice=cluster.servers[0])
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
            worker = KVWorker(0, 0, postoffice=cluster.workers[0])
            losses = kv_train_loop(worker, cfg, steps=150, lr=0.1,
                                   codec=codec)
            worker.stop()
        finally:
            for s in servers:
                s.stop()
            cluster.finalize()
        return losses

    def tail(losses):  # mean of the last few steps: step noise damped
        return float(np.mean(losses[-5:]))

    base = run(codec=None, ef=True)
    fp8_ef = run(codec="fp8_e4m3", ef=True)
    fp8_noef = run(codec="fp8_e4m3", ef=False)
    assert np.isfinite(base).all() and np.isfinite(fp8_ef).all()
    # The uncompressed run must actually learn, or parity is vacuous.
    assert tail(base) < base[0] * 0.1, base
    # Convergence guard: fp8+EF within 2% of the uncompressed final
    # loss.
    gap_ef = abs(tail(fp8_ef) - tail(base)) / tail(base)
    gap_noef = abs(tail(fp8_noef) - tail(base)) / tail(base)
    assert gap_ef <= 0.02, (
        f"fp8_e4m3+EF final loss {tail(fp8_ef):.4f} vs uncompressed "
        f"{tail(base):.4f} (gap {gap_ef:.1%} > 2%); EF-disabled gap "
        f"for reference: {gap_noef:.1%}"
    )
    # Documented: the EF-disabled gap in this regime (both runs must
    # at least train to convergence; the bias EF removes is asserted
    # at the codec level in test_ops).
    assert np.isfinite(fp8_noef).all() and tail(fp8_noef) < base[0] * 0.2, (
        f"fp8_e4m3 without EF failed to train: final "
        f"{tail(fp8_noef):.4f} (EF gap {gap_ef:.2%}, "
        f"no-EF gap {gap_noef:.2%})"
    )


def test_train_step_remat_matches():
    """cfg.remat trades FLOPs for activation memory without changing the
    math: losses match the non-remat config."""
    import jax
    import numpy as np

    from pslite_tpu.models.train import make_ps_train_step, toy_batch
    from pslite_tpu.models.transformer import ModelConfig
    from pslite_tpu.parallel.mesh import make_mesh

    mesh = make_mesh((2, 4), ("dp", "sp"))
    losses = {}
    for remat in (False, True):
        cfg = ModelConfig(vocab=64, dim=32, heads=2, layers=2, remat=remat)
        step, store, tok_sharding, _ = make_ps_train_step(cfg, mesh, lr=0.1)
        inputs, targets = toy_batch(cfg, batch=2, seq=16)
        inputs = jax.device_put(inputs, tok_sharding)
        targets = jax.device_put(targets, tok_sharding)
        # TWO steps: the step-2 loss depends on step-1's GRADIENTS (the
        # store update), which is exactly what remat recomputes — a
        # single-step loss would be a pre-update tautology.
        store, _ = step(store, inputs, targets)
        store, loss2 = step(store, inputs, targets)
        losses[remat] = float(loss2)
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)
