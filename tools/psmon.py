#!/usr/bin/env python
"""psmon — live cluster-wide telemetry monitor (docs/observability.md).

Asks the scheduler for a ``METRICS_PULL`` snapshot of every node's
metrics registry and renders one table row per node (request-latency
quantiles, lane depth, apply-shard throughput, retransmits, replication
forwards/lag) plus per-role rollups and each server's hottest keys.

Library use (in-process clusters, tests, notebooks)::

    from tools import psmon
    snap = psmon.collect(scheduler_postoffice)   # {node_id: snapshot}
    print(psmon.format_table(snap))              # or psmon.to_json(snap)

CLI: ``python tools/psmon.py [--json]`` boots a live demo
LoopbackCluster (2 workers, 2 servers, scheduler), drives a short
push/pull storm, pulls the cluster snapshot through the scheduler, and
prints it — the end-to-end proof of the pull plane without needing an
external deployment to attach to.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

# Script use from anywhere: put the repo root ahead of tools/.
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def collect(scheduler_po, timeout_s: float = 5.0) -> Dict[int, dict]:
    """Cluster snapshot via the scheduler's METRICS_PULL broadcast:
    ``{node_id: telemetry_snapshot}`` (nodes that failed to answer
    within the timeout are absent)."""
    return scheduler_po.collect_cluster_metrics(timeout_s=timeout_s)


def to_json(snap: Dict[int, dict]) -> str:
    return json.dumps({str(k): v for k, v in sorted(snap.items())},
                      indent=2, sort_keys=True)


def _hist_q(m: dict, name: str, q: str) -> float:
    h = m.get("histograms", {}).get(name)
    return h.get(q, 0.0) if h else 0.0


def _c(m: dict, name: str) -> int:
    return int(m.get("counters", {}).get(name, 0))


def _g(m: dict, name: str) -> float:
    return float(m.get("gauges", {}).get(name, 0.0))


def _req_quantiles(m: dict) -> tuple:
    """Merged push/pull request-latency (p50, p99) in ms — worker side."""
    hp = m.get("histograms", {}).get("kv.push_latency_s") or {}
    hl = m.get("histograms", {}).get("kv.pull_latency_s") or {}
    # Weighted pick: report the busier path's quantiles (a true merged
    # quantile would need the raw buckets of both; close enough for a
    # monitor row — the JSON dump has both histograms in full).
    busy = hp if hp.get("count", 0) >= hl.get("count", 0) else hl
    return busy.get("p50", 0.0) * 1e3, busy.get("p99", 0.0) * 1e3


def _apply_row(m: dict, uptime: float) -> tuple:
    n = _c(m, "apply.sharded_requests") + _c(m, "apply.global_requests")
    rate = n / uptime if uptime > 0 else 0.0
    depth = sum(
        v for k, v in m.get("gauges", {}).items()
        if k.startswith("apply.shard") and k.endswith(".depth")
    )
    return n, rate, depth


def format_table(snap: Dict[int, dict], top_keys: int = 3) -> str:
    """Human-readable per-node table + per-role and per-tenant
    rollups (docs/qos.md)."""
    # ``epoch`` (elastic membership) and ``ops/F`` (small-op batching)
    # ride LAST, in landing order: existing consumers parse earlier
    # columns by index.
    hdr = (f"{'node':>5} {'role':>9} {'up_s':>7} {'req_p50ms':>9} "
           f"{'req_p99ms':>9} {'lane_q':>6} {'xfers':>6} {'apply_n':>8} "
           f"{'apply/s':>8} {'retx':>6} {'repl_fwd':>8} {'repl_lag':>8} "
           f"{'cmpr':>6} {'cache%':>6} {'sent':>7} {'recv':>7} "
           f"{'epoch':>5} {'ops/F':>6} {'resp ops/F':>10}")
    lines = [hdr, "-" * len(hdr)]
    rollup: Dict[str, Dict[str, float]] = {}
    # Elastic membership (docs/elasticity.md): per-node routing epoch
    # and, for servers, the key ranges they own under it.
    membership_lines: List[str] = []
    # Per-tenant request/shed totals across the cluster (the server-
    # side ``tenant.<name>.requests`` / ``.shed`` counters).
    tenants: Dict[str, Dict[str, int]] = {}
    hot_lines: List[str] = []
    for node_id in sorted(snap):
        s = snap[node_id]
        m = s.get("metrics", {})
        uptime = float(m.get("uptime_s", 0.0))
        p50, p99 = _req_quantiles(m)
        apply_n, apply_rate, _apply_depth = _apply_row(m, uptime)
        lane_q = _g(m, "van.lane_depth")
        # In-flight chunked transfers (partially reassembled) on this
        # node — docs/chunking.md; a persistently nonzero value with
        # idle traffic means leaked reassembly state.
        xfers = _g(m, "van.xfers_inflight")
        retx = _c(m, "resender.retransmits")
        fwd = _c(m, "replication.forwards")
        lag = _g(m, "replication.lag")
        sent = _c(m, "van.sent_messages")
        recv = _c(m, "van.recv_messages")
        # Wire-compression ratio this node ENCODED at (codec tier,
        # docs/compression.md): raw payload bytes / wire bytes.  "-"
        # when the node encoded nothing (or PS_TELEMETRY=0).
        craw = _c(m, "codec.raw_bytes")
        cwire = _c(m, "codec.wire_bytes")
        cmpr = f"{craw / cwire:>6.1f}" if cwire > 0 else f"{'-':>6}"
        # Hot-key cache hit rate (kv/hot_cache.py): worker-side; "-"
        # when the node never consulted a cache (PS_HOT_CACHE off).
        hits = _c(m, "kv.hot_cache.hits")
        misses = _c(m, "kv.hot_cache.misses")
        cache = (f"{100.0 * hits / (hits + misses):>5.1f}%"
                 if hits + misses > 0 else f"{'-':>6}")
        role = s.get("role", "?")
        routing = s.get("routing") or {}
        epoch = (f"{routing['epoch']:>5}" if "epoch" in routing
                 else f"{'-':>5}")
        # Small-op aggregation depth this node SENT at (docs/
        # batching.md): sub-ops per multi-op frame, split by
        # direction — request frames (worker op combiner) and
        # response frames (server batched group responses + response
        # combiner, the serving fan-in plane).  "-" when the node
        # never emitted an EXT_BATCH frame in that direction
        # (combiner off, nothing coalesced, or PS_TELEMETRY=0).
        bframes = _c(m, "van.batched_frames")
        bops = _c(m, "van.batch_ops")
        opsf = (f"{bops / bframes:>6.1f}" if bframes > 0 else f"{'-':>6}")
        rframes = _c(m, "van.resp_batched_frames")
        rops = _c(m, "van.resp_batch_ops")
        ropsf = (f"{rops / rframes:>10.1f}" if rframes > 0
                 else f"{'-':>10}")
        lines.append(
            f"{node_id:>5} {role:>9} {uptime:>7.1f} {p50:>9.3f} "
            f"{p99:>9.3f} {lane_q:>6.0f} {xfers:>6.0f} {apply_n:>8} "
            f"{apply_rate:>8.1f} {retx:>6} {fwd:>8} {lag:>8.0f} "
            f"{cmpr} {cache} {sent:>7} {recv:>7} {epoch} {opsf} {ropsf}"
        )
        if routing:
            owned = routing.get("owned")
            if owned is not None:
                pretty = (", ".join(f"[{b:#x}, {e:#x})" for b, e in owned)
                          or "(none)")
                membership_lines.append(
                    f"  node {node_id} ({role}) epoch "
                    f"{routing.get('epoch')}: owns {pretty}"
                )
            elif role == "scheduler":
                membership_lines.append(
                    f"  active ranks: {routing.get('active')}  leaving: "
                    f"{routing.get('leaving')}  (epoch "
                    f"{routing.get('epoch')})"
                )
        for cname, cval in m.get("counters", {}).items():
            # tenant.<name>.<kind> — names are identifier-like (the
            # PS_TENANTS parser rejects dots), but rsplit keeps this
            # robust to any counter shape regardless.
            if cname.startswith("tenant.") and cname.count(".") >= 2:
                tname, kind = cname[len("tenant."):].rsplit(".", 1)
                t = tenants.setdefault(tname, {"requests": 0, "shed": 0})
                if kind in t:
                    t[kind] += int(cval)
        r = rollup.setdefault(role, {"nodes": 0, "sent": 0, "recv": 0,
                                     "apply": 0, "retx": 0, "fwd": 0})
        r["nodes"] += 1
        r["sent"] += sent
        r["recv"] += recv
        r["apply"] += apply_n
        r["retx"] += retx
        r["fwd"] += fwd
        top = m.get("topk", {}).get("kv.hot_keys") or []
        if top:
            pretty = ", ".join(f"{k}:{n}" for k, n in top[:top_keys])
            hot_lines.append(f"  node {node_id} ({role}) hot keys: {pretty}")
    lines.append("")
    lines.append("per-role rollup:")
    for role in sorted(rollup):
        r = rollup[role]
        lines.append(
            f"  {role:>9}: {int(r['nodes'])} node(s), "
            f"sent={int(r['sent'])} recv={int(r['recv'])} "
            f"apply={int(r['apply'])} retx={int(r['retx'])} "
            f"repl_fwd={int(r['fwd'])}"
        )
    if tenants:
        lines.append("")
        lines.append("per-tenant rollup (docs/qos.md):")
        for tname in sorted(tenants):
            t = tenants[tname]
            total = t["requests"]
            shed_pct = 100.0 * t["shed"] / total if total else 0.0
            lines.append(
                f"  {tname:>9}: requests={total} shed={t['shed']} "
                f"({shed_pct:.1f}%)"
            )
    if membership_lines:
        lines.append("")
        lines.append("elastic membership (docs/elasticity.md):")
        lines.extend(membership_lines)
    if hot_lines:
        lines.append("")
        lines.extend(hot_lines)
    return "\n".join(lines)


def _demo(as_json: bool) -> int:
    """Boot a live 2w+2s LoopbackCluster, run a short storm, snapshot
    through the scheduler, print.  The standalone proof of the pull
    plane (library callers attach to their own scheduler instead)."""
    import numpy as np

    from pslite_tpu.benchmark import _loopback_cluster, _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker)

    nodes = _loopback_cluster(num_workers=2, num_servers=2,
                              ns="psmon-demo")
    scheduler, server_pos, worker_pos = nodes[0], nodes[1:3], nodes[3:]
    servers = []
    workers = []
    try:
        for po in server_pos:
            srv = KVServer(0, postoffice=po)
            srv.set_request_handle(KVServerDefaultHandle())
            servers.append(srv)
        workers = [KVWorker(0, 0, postoffice=po) for po in worker_pos]
        keys = np.array([3, 2 ** 62, 2 ** 63 + 9], dtype=np.uint64)
        vals = np.ones(3 * 128, dtype=np.float32)
        out = np.zeros_like(vals)
        for _ in range(20):
            for w in workers:
                w.wait(w.push(keys, vals))
        workers[0].wait(workers[0].pull(keys, out))
        snap = collect(scheduler)
        print(to_json(snap) if as_json else format_table(snap))
    finally:
        _teardown_cluster(nodes, workers, servers)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="dump the raw snapshot as JSON")
    args = ap.parse_args(argv)
    return _demo(args.json)


if __name__ == "__main__":
    sys.exit(main())
