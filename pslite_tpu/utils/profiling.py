"""Per-message event tracing.

Equivalent of the reference's ``USE_PROFILING`` van tracing
(``src/van.cc:29-77, 440-457``): when ``ENABLE_PROFILING`` is set, every
push/pull send/recv appends ``key,event,timestamp_us`` to a role-tagged file
(``PROFILE_PATH`` or ``pslite_profile_van_<role>_<ts>``).  For device-side
timelines use ``jax.profiler`` traces; this file-based log covers the
control/DCN plane the same way the reference covers its NICs.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class MonotonicAnchor:
    """One wall-clock anchor plus monotonic offsets: timestamps that
    can never go backwards within a stream (NTP steps used to corrupt
    durations) yet merge across nodes on a shared wall timeline.  THE
    single timebase of the event log (Profiler) and the distributed
    tracer (telemetry/tracing.py) — two private copies of this formula
    would skew cross-file merge alignment if they ever drifted."""

    __slots__ = ("wall_ns", "mono_ns")

    def __init__(self):
        self.wall_ns = time.time_ns()
        self.mono_ns = time.monotonic_ns()

    def now_ns(self) -> int:
        return self.wall_ns + (time.monotonic_ns() - self.mono_ns)


class Profiler:
    # Events between explicit flushes: small enough that a crash loses
    # at most a syscall's worth of tail, large enough to stay off the
    # per-event hot path.
    _FLUSH_EVERY = 256

    def __init__(self, env, role: str):
        self._enabled = bool(env.find_int("ENABLE_PROFILING", 0))
        self._fh = None
        self._mu = threading.Lock()
        self._since_flush = 0
        self._anchor = MonotonicAnchor()
        if self._enabled:
            path = env.find("PROFILE_PATH")
            if not path:
                path = f"pslite_profile_van_{role}_{int(time.time())}"
            self._fh = open(path, "a")

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def closed(self) -> bool:
        """True when an enabled profiler's log was closed (Van.stop);
        a restarted van re-creates the profiler instead of silently
        dropping every event of its second life."""
        return self._enabled and self._fh is None

    def _ts_us(self) -> int:
        return self._anchor.now_ns() // 1000

    def _write(self, line: str) -> None:
        with self._mu:
            if self._fh is None:
                return
            self._fh.write(line)
            self._since_flush += 1
            if self._since_flush >= self._FLUSH_EVERY:
                self._fh.flush()
                self._since_flush = 0

    def record(self, key: int, event: str, push: bool) -> None:
        if not self._enabled or self._fh is None:
            return
        kind = "push" if push else "pull"
        self._write(f"{key},{event}_{kind},{self._ts_us()}\n")

    def record_engine(self, bucket: str, op: str, nbytes: int,
                      dur_us: int) -> None:
        """Collective data-plane event: ``bucket,<op>_engine,ts,bytes,µs``
        — the engine-path extension of the reference's (key, event, µs)
        log, so ENABLE_PROFILING covers the flagship transport too."""
        if not self._enabled or self._fh is None:
            return
        self._write(
            f"{bucket},{op}_engine,{self._ts_us()},{nbytes},{dur_us}\n"
        )

    def close(self) -> None:
        if self._fh is not None:
            with self._mu:
                if self._fh is not None:
                    self._fh.flush()
                    self._fh.close()
                    self._fh = None


def clocked(loop, measure=None):
    """Seconds taken by ``loop()`` — host wall clock by default, or
    whatever clock ``measure(loop) -> seconds | None`` implements (e.g.
    XPlane device-busy seconds).  The ONE definition of the clock-swap
    scaffold shared by the model replays and the stress patterns; None
    means the requested basis is unavailable and must propagate (never
    substitute a fake number)."""
    if measure is not None:
        return measure(loop)
    t0 = time.perf_counter()
    loop()
    return time.perf_counter() - t0


class device_trace:
    """Device-side timeline capture (jax.profiler / XPlane).

    The TPU counterpart of the van's per-message event log: wrap the hot
    region and open the trace in TensorBoard/XProf::

        with device_trace("/tmp/ps_trace"):
            engine.push_pull("grads", g)
            engine.block()
    """

    def __init__(self, log_dir: str):
        self._log_dir = log_dir
        self._ctx = None

    def __enter__(self):
        import jax

        self._ctx = jax.profiler.trace(self._log_dir)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)
