"""Versioned key-range routing for elastic membership
(docs/elasticity.md).

The scheduler owns ONE :class:`RoutingTable` — an epoch-stamped
assignment of contiguous key ranges to server group ranks — and
broadcasts it (``Command.ROUTING``, JSON in ``meta.body``) on every
membership change.  It replaces the static
``Postoffice.get_server_key_ranges`` uniform split the moment a cluster
becomes elastic (``PS_ELASTIC=1``):

- **Workers** slice every push/pull over ``entries`` and send each
  slice to its entry's ``owner`` rank (not the entry index), so the
  number of entries may exceed the number of servers (a server that
  absorbed a decommissioned neighbor's range owns two entries until
  they coalesce on the next epoch).
- **Servers** read the table to learn what they own; an entry whose
  ``prev`` names another rank IS the migration plan — the previous
  owner streams the range's state to the new owner (chunked, replica-
  style), and the new owner parks requests for the range until the
  handoff lands.
- **Epochs** are strictly increasing; every node applies a table only
  when its epoch exceeds the one it holds, so reordered broadcasts can
  never roll routing backwards.

Tables are immutable: every membership change derives a NEW table via
:meth:`with_join` / :meth:`with_leave` / :meth:`with_departed`.
``active`` is the set of live server ranks (rank holes are legal after
an out-of-order decommission — the node-id tables and replica chains
follow it, not ``num_servers``); ``leaving`` marks ranks that are
mid-decommission: still addressable, already owning nothing.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .base import MAX_KEY
from .range import Range
from .utils import logging as log


@dataclass(frozen=True)
class RouteEntry:
    """One contiguous key range and its owning server group rank.
    ``prev`` != -1 marks an ownership change THIS epoch: ``prev`` must
    migrate the range's state to ``owner`` (the migration plan rides
    the table itself, so receivers never need the previous epoch)."""

    begin: int
    end: int
    owner: int
    prev: int = -1


@dataclass(frozen=True)
class RoutingTable:
    epoch: int
    num_servers: int                    # max(active) + 1 — id-table sizing
    active: Tuple[int, ...]             # live server group ranks, sorted
    leaving: Tuple[int, ...] = ()       # mid-decommission (own nothing)
    entries: Tuple[RouteEntry, ...] = ()

    # -- construction --------------------------------------------------------

    @staticmethod
    def initial(num_servers: int) -> "RoutingTable":
        """Epoch-0 table: the exact uniform split of
        ``Postoffice.get_server_key_ranges`` (postoffice.cc:257-268),
        so a cluster that never changes membership routes identically
        to a static one."""
        log.check(num_servers > 0, "routing needs >= 1 server")
        span = MAX_KEY // num_servers
        entries = tuple(
            RouteEntry(
                begin=span * i,
                end=span * (i + 1) if i + 1 < num_servers else MAX_KEY,
                owner=i,
            )
            for i in range(num_servers)
        )
        return RoutingTable(
            epoch=0, num_servers=num_servers,
            active=tuple(range(num_servers)), entries=entries,
        )

    def _settled(self) -> List[RouteEntry]:
        """Entries with last epoch's migration markers cleared and
        adjacent same-owner entries coalesced — the base every new
        epoch derives from."""
        out: List[RouteEntry] = []
        for e in sorted(self.entries, key=lambda e: e.begin):
            if out and out[-1].owner == e.owner and out[-1].end == e.begin:
                out[-1] = RouteEntry(out[-1].begin, e.end, e.owner)
            else:
                out.append(RouteEntry(e.begin, e.end, e.owner))
        return out

    def _range_load(self, begin: int, end: int,
                    hot: Optional[Dict[int, int]]) -> int:
        if not hot:
            return 0
        return sum(n for k, n in hot.items() if begin <= k < end)

    def with_join(self, rank: int,
                  hot: Optional[Dict[int, int]] = None) -> "RoutingTable":
        """Admit server ``rank``: split the most loaded range (by the
        ``kv.hot_keys`` hint when the scheduler has one, else the
        widest) and hand the upper half to the joiner, marked for
        migration from the donor."""
        log.check(rank not in self.active,
                  f"rank {rank} is already a member")
        base = self._settled()
        splittable = [e for e in base if e.end - e.begin >= 2]
        log.check(bool(splittable), "no splittable range left")
        loads = [self._range_load(e.begin, e.end, hot) for e in splittable]
        if any(loads):
            donor = splittable[loads.index(max(loads))]
        else:
            donor = max(splittable, key=lambda e: e.end - e.begin)
        # Load-weighted cut: split at the median hot key of the donor
        # range so the two halves carry comparable traffic; cold ranges
        # split at the byte midpoint.
        cut = donor.begin + (donor.end - donor.begin) // 2
        if hot:
            inside = sorted(k for k in hot if donor.begin <= k < donor.end)
            if inside:
                cut = inside[len(inside) // 2]
        cut = min(max(cut, donor.begin + 1), donor.end - 1)
        out: List[RouteEntry] = []
        for e in base:
            if e is donor:
                out.append(RouteEntry(e.begin, cut, e.owner))
                out.append(RouteEntry(cut, e.end, rank, prev=e.owner))
            else:
                out.append(e)
        active = tuple(sorted(set(self.active) | {rank}))
        return RoutingTable(
            epoch=self.epoch + 1, num_servers=max(active) + 1,
            active=active, leaving=tuple(r for r in self.leaving
                                         if r != rank),
            entries=tuple(out),
        )

    def with_rebalance(self, src: int, dst: int,
                       hot: Optional[Dict[int, int]] = None
                       ) -> "RoutingTable":
        """Shift load from ``src`` to ``dst`` (both live members):
        move ``src``'s most loaded range to ``dst`` outright when
        ``src`` owns several, else split it (median hot key, byte
        midpoint when cold) and hand the hotter half over — marked for
        migration so the existing handoff machinery moves the state.
        Membership is unchanged; only ownership shifts.  This is the
        autopilot's skew actuator (docs/autopilot.md)."""
        log.check(src in self.active, f"rank {src} is not a member")
        log.check(dst in self.active, f"rank {dst} is not a member")
        log.check(src != dst, "rebalance needs two distinct ranks")
        log.check(dst not in self.leaving,
                  f"rank {dst} is mid-decommission")
        base = self._settled()
        owned = [e for e in base if e.owner == src]
        log.check(bool(owned), f"rank {src} owns no range")
        loads = [self._range_load(e.begin, e.end, hot) for e in owned]
        victim = (owned[loads.index(max(loads))] if any(loads)
                  else max(owned, key=lambda e: e.end - e.begin))
        out: List[RouteEntry] = []
        for e in base:
            if e is not victim:
                out.append(e)
                continue
            if len(owned) > 1 or e.end - e.begin < 2:
                # Whole-entry move: src keeps its other holdings (or
                # the range is too narrow to split).
                out.append(RouteEntry(e.begin, e.end, dst, prev=src))
                continue
            # src's only range: split it and hand over the HOTTER half
            # (ties go to the upper half, matching with_join's cut).
            cut = e.begin + (e.end - e.begin) // 2
            inside = []
            if hot:
                inside = sorted(k for k in hot if e.begin <= k < e.end)
                if inside:
                    cut = inside[len(inside) // 2]
            cut = min(max(cut, e.begin + 1), e.end - 1)
            lower_mass = self._range_load(e.begin, cut, hot)
            upper_mass = self._range_load(cut, e.end, hot)
            if lower_mass > upper_mass:
                out.append(RouteEntry(e.begin, cut, dst, prev=src))
                out.append(RouteEntry(cut, e.end, src))
            else:
                out.append(RouteEntry(e.begin, cut, src))
                out.append(RouteEntry(cut, e.end, dst, prev=src))
        return RoutingTable(
            epoch=self.epoch + 1, num_servers=self.num_servers,
            active=self.active, leaving=self.leaving,
            entries=tuple(out),
        )

    def with_leave(self, rank: int) -> "RoutingTable":
        """Begin decommissioning ``rank``: every range it owns is
        reassigned to the owner of an adjacent range (keeping each
        survivor's holdings contiguous) and marked for migration.
        ``rank`` stays in ``active`` (it must keep serving the
        migration and WRONG_OWNER bounces) and joins ``leaving`` until
        :meth:`with_departed` retires it."""
        log.check(rank in self.active, f"rank {rank} is not a member")
        log.check(len(self.active) >= 2,
                  "cannot decommission the last server")
        base = self._settled()
        out: List[RouteEntry] = []
        for i, e in enumerate(base):
            if e.owner != rank:
                out.append(e)
                continue
            heir = next(
                (base[j].owner
                 for j in list(range(i + 1, len(base)))
                 + list(range(i - 1, -1, -1))
                 if base[j].owner != rank),
                None,
            )
            log.check(heir is not None, "no surviving heir rank")
            out.append(RouteEntry(e.begin, e.end, heir, prev=rank))
        return RoutingTable(
            epoch=self.epoch + 1, num_servers=self.num_servers,
            active=self.active,
            leaving=tuple(sorted(set(self.leaving) | {rank})),
            entries=tuple(out),
        )

    def with_departed(self, rank: int) -> "RoutingTable":
        """Retire a decommissioned rank: its migrations completed, so
        it leaves the membership entirely (node tables, barriers, the
        failure detector's expectations, and replica chains all stop
        counting it)."""
        entries = self._settled()
        log.check(all(e.owner != rank for e in entries),
                  f"rank {rank} still owns ranges; with_leave first")
        active = tuple(r for r in self.active if r != rank)
        log.check(bool(active), "cannot retire the last server")
        return RoutingTable(
            epoch=self.epoch + 1, num_servers=max(active) + 1,
            active=active,
            leaving=tuple(r for r in self.leaving if r != rank),
            entries=tuple(entries),
        )

    # -- queries -------------------------------------------------------------

    def owner_of(self, key: int) -> int:
        for e in self.entries:
            if e.begin <= key < e.end:
                return e.owner
        return self.entries[-1].owner if self.entries else 0

    def ranges_of(self, rank: int) -> List[Range]:
        return [Range(e.begin, e.end) for e in self.entries
                if e.owner == rank]

    def migrations(self) -> List[RouteEntry]:
        """Entries changing hands this epoch (the migration plan)."""
        return [e for e in self.entries
                if e.prev not in (-1, e.owner)]

    # -- wire ----------------------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "epoch": self.epoch,
            "num_servers": self.num_servers,
            "active": list(self.active),
            "leaving": list(self.leaving),
            "entries": [[e.begin, e.end, e.owner, e.prev]
                        for e in self.entries],
        })

    @staticmethod
    def from_json(raw) -> "RoutingTable":
        if isinstance(raw, (bytes, bytearray)):
            raw = raw.decode()
        d = json.loads(raw)
        return RoutingTable(
            epoch=int(d["epoch"]),
            num_servers=int(d["num_servers"]),
            active=tuple(int(r) for r in d["active"]),
            leaving=tuple(int(r) for r in d.get("leaving", ())),
            entries=tuple(
                RouteEntry(int(b), int(e), int(o), int(p))
                for b, e, o, p in d["entries"]
            ),
        )
