"""Wire-plane observatory: syscall / frame / byte / occupancy accounting.

The telemetry plane (ClusterHistory, SLO watchdog, tracing) stops at the
op layer; this module instruments the layer below it — the wire.  Every
van owns a :class:`WireStats` that records, per direction:

- ``wire.tx.ops`` / ``wire.rx.ops`` — logical operations (messages
  entering ``Van.send`` / surfacing from the receive loop),
- ``wire.tx.frames`` / ``wire.rx.frames`` — wire frames (chunks count
  individually, so frames/op exposes chunking amplification),
- ``wire.tx.syscalls`` / ``wire.rx.syscalls`` — kernel entries
  (``sendmsg`` / ``recv_into`` calls; the denominator of the io_uring
  van's "syscalls/op < 0.1" target),
- ``wire.tx.bytes_zc`` vs ``wire.tx.bytes_copy`` — payload bytes handed
  to the kernel as borrowed views vs serialized/copied header+meta
  bytes (same split on rx: scatter-into-destination vs pooled copy),
- ``wire.lane.<peer>.tx.frames`` / ``.tx.bytes`` — per-lane traffic,
  cardinality-capped (see below),
- histogram ``wire.batch_occupancy`` — ops per combiner-emitted frame
  (including singleton runs, so the fill distribution is honest),
- histogram ``wire.lane_residency_s`` — queue wait between lane enqueue
  and dispatch.

The native C++ plane exports the same families under ``wire.native.*``,
synced from the one-struct FFI snapshot (:func:`WireStats.sync_native`).

Cost model — **thread-local shards, flushed off the hot path**:
recording is two int adds and a compare on a per-thread shard object (no
lock, no registry lookup); every ``PS_WIRE_FLUSH_OPS`` (default 64)
records the owning thread folds the shard into the node registry
(counters are bare int adds; histograms merge pre-bucketed arrays under
one lock via ``Histogram.merge_shard``).  ``flush()`` from the snapshot
path drains all shards so ``METRICS_PULL`` never reads a stale plane;
cross-thread drains tolerate the same rare lost increment the metrics
module already documents.

Cardinality: lane labels are bounded at ``PS_WIRE_MAX_LANES`` (default
16) distinct peers per van; traffic beyond the cap aggregates into
``wire.lane.other.*`` so a large cluster cannot explode the registry.

``PS_WIRE_TELEMETRY=0`` (or a disabled node registry) swaps in the
shared :data:`NULL_WIRE` no-op — call sites pay one attribute call on a
do-nothing method and the send path is bit-identical on the wire.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .. import environment
from .metrics import Registry

# Registry metric-name roots (the catalogue above; docs/observability.md).
_TX = "wire.tx."
_RX = "wire.rx."
_LANE = "wire.lane."
_NATIVE = "wire.native."
OCCUPANCY_HIST = "wire.batch_occupancy"
RESIDENCY_HIST = "wire.lane_residency_s"
_OCC_LO = 1.0       # bucket floor: occupancy is an op count
_RES_LO = 1e-6      # bucket floor: residency is seconds (1 µs)
_NBUCKETS = 64      # must match metrics.Histogram.NBUCKETS

# Native snapshot field -> registry counter suffix (under wire.native.).
_NATIVE_FIELDS = (
    ("tx_syscalls", "tx.syscalls"),
    ("tx_frames", "tx.frames"),
    ("tx_chunks", "tx.chunks"),
    ("tx_bytes", "tx.bytes_zc"),
    ("tx_msgs", "tx.ops"),
    ("rx_syscalls", "rx.syscalls"),
    ("rx_frames", "rx.frames"),
    ("rx_bytes_copy", "rx.bytes_copy"),
    ("rx_bytes_zc", "rx.bytes_zc"),
    ("rx_pool_hits", "rx.pool_hits"),
    ("rx_pool_misses", "rx.pool_misses"),
)


class _ShardHist:
    """Per-thread pre-bucketed histogram half: observes into a private
    ``{bucket: count}`` dict with the same log2 geometry as the registry
    histogram it flushes into."""

    __slots__ = ("lo", "count", "sum", "min", "max", "buckets")

    def __init__(self, lo: float):
        self.lo = lo
        self.reset()

    def reset(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.buckets: Dict[int, int] = {}

    def observe(self, v: float) -> None:
        if v <= self.lo:
            i = 0
        else:
            i = min(_NBUCKETS - 1, int(v / self.lo).bit_length())
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.buckets[i] = self.buckets.get(i, 0) + 1


class _Shard:
    """One thread's unflushed wire accounting (plain ints, no lock)."""

    __slots__ = ("pending", "tx_ops", "tx_frames", "tx_syscalls",
                 "tx_bytes_copy", "tx_bytes_zc", "rx_ops", "rx_frames",
                 "rx_syscalls", "rx_bytes_copy", "rx_bytes_zc",
                 "lanes", "lane_id", "lane_ent", "occupancy",
                 "residency")

    def __init__(self):
        self.pending = 0
        self.tx_ops = 0
        self.tx_frames = 0
        self.tx_syscalls = 0
        self.tx_bytes_copy = 0
        self.tx_bytes_zc = 0
        self.rx_ops = 0
        self.rx_frames = 0
        self.rx_syscalls = 0
        self.rx_bytes_copy = 0
        self.rx_bytes_zc = 0
        # peer id -> [frames, bytes] (tx direction; rx lanes would double
        # cardinality for a mirror of sender-side truth).  lane_id/
        # lane_ent memoize the last-hit entry: lane-sender threads are
        # per-peer, so a shard's lane is all but constant.
        self.lanes: Dict[object, list] = {}
        self.lane_id: object = None
        self.lane_ent: Optional[list] = None
        self.occupancy = _ShardHist(_OCC_LO)
        self.residency = _ShardHist(_RES_LO)


class WireStats:
    """Per-van wire accounting; see the module docstring for the metric
    catalogue and cost model.  Construct via :func:`make_wire_stats`."""

    enabled = True

    def __init__(self, registry: Registry, env=None):
        env = env if env is not None else environment.get()
        self._reg = registry
        self.flush_ops = max(1, env.find_int("PS_WIRE_FLUSH_OPS", 64))
        self.max_lanes = max(1, env.find_int("PS_WIRE_MAX_LANES", 16))
        self._tls = threading.local()
        self._mu = threading.Lock()
        self._shards: list = []
        self._lane_ids: set = set()
        # Amortization ledger: records vs flushes (tests + pssoak's
        # telemetry-overhead self-measurement both read these).
        self._c_records = registry.counter("wire.telemetry.records")
        self._c_flushes = registry.counter("wire.telemetry.flushes")
        # Flush targets resolved ONCE: a registry lookup per counter
        # per flush would dominate the amortized per-record cost.
        self._flush_counters = tuple(
            (attr, registry.counter(name)) for attr, name in (
                ("tx_ops", _TX + "ops"), ("tx_frames", _TX + "frames"),
                ("tx_syscalls", _TX + "syscalls"),
                ("tx_bytes_copy", _TX + "bytes_copy"),
                ("tx_bytes_zc", _TX + "bytes_zc"),
                ("rx_ops", _RX + "ops"), ("rx_frames", _RX + "frames"),
                ("rx_syscalls", _RX + "syscalls"),
                ("rx_bytes_copy", _RX + "bytes_copy"),
                ("rx_bytes_zc", _RX + "bytes_zc")))
        self._h_occupancy = registry.histogram(OCCUPANCY_HIST, _OCC_LO)
        self._h_residency = registry.histogram(RESIDENCY_HIST, _RES_LO)
        # Native-plane absolute counters from the last sync.
        self._native_last: Dict[str, int] = {}

    # -- hot-path recording (thread-local shard, no lock) ----------------
    #
    # Each recorder inlines the shard fetch (try/except beats a method
    # call plus 3-arg getattr) and the flush tick: the common case is
    # a handful of int adds and one compare, nothing else.

    def _new_shard(self) -> _Shard:
        s = _Shard()
        self._tls.shard = s
        with self._mu:
            self._shards.append(s)
        return s

    def tx_op(self, n: int = 1) -> None:
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.tx_ops += n
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def tx_frame(self, lane, zc_bytes: int, copy_bytes: int = 0,
                 frames: int = 1) -> None:
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.tx_frames += frames
        s.tx_bytes_zc += zc_bytes
        s.tx_bytes_copy += copy_bytes
        if lane is not None:
            if lane == s.lane_id and s.lane_ent is not None:
                ent = s.lane_ent
            else:
                ent = s.lanes.get(lane)
                if ent is None:
                    ent = s.lanes[lane] = [0, 0]
                s.lane_id = lane
                s.lane_ent = ent
            ent[0] += frames
            ent[1] += zc_bytes + copy_bytes
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def tx_msg(self, ops: int) -> None:
        """One Python-plane data frame leaving ``Van.send``: logical
        ops AND the combiner-occupancy observation in a single shard
        visit (the two always travel together on this plane)."""
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.tx_ops += ops
        s.occupancy.observe(float(ops))
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def tx_syscalls(self, n: int = 1) -> None:
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.tx_syscalls += n
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def rx_op(self, n: int = 1) -> None:
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.rx_ops += n
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def rx_frame(self, zc_bytes: int, copy_bytes: int = 0,
                 frames: int = 1) -> None:
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.rx_frames += frames
        s.rx_bytes_zc += zc_bytes
        s.rx_bytes_copy += copy_bytes
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def rx_msg(self, ops: int, zc_bytes: int,
               copy_bytes: int = 0) -> None:
        """One data message surfacing from the receive pump: logical
        ops and its frame/byte accounting in a single shard visit."""
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.rx_ops += ops
        s.rx_frames += 1
        s.rx_bytes_zc += zc_bytes
        s.rx_bytes_copy += copy_bytes
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def rx_syscalls(self, n: int = 1) -> None:
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.rx_syscalls += n
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def batch_occupancy(self, ops: int) -> None:
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.occupancy.observe(float(ops))
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    def lane_residency(self, wait_s: float) -> None:
        try:
            s = self._tls.shard
        except AttributeError:
            s = self._new_shard()
        s.residency.observe(wait_s)
        s.pending += 1
        if s.pending >= self.flush_ops:
            self._flush_shard(s)

    # -- flushing --------------------------------------------------------

    def _lane_key(self, lane) -> str:
        key = str(lane)
        if key in self._lane_ids:
            return key
        with self._mu:
            if key in self._lane_ids:
                return key
            if len(self._lane_ids) < self.max_lanes:
                self._lane_ids.add(key)
                return key
        return "other"

    def _flush_shard(self, s: _Shard) -> None:
        reg = self._reg
        records, s.pending = s.pending, 0
        for attr, counter in self._flush_counters:
            v = getattr(s, attr)
            if v:
                setattr(s, attr, 0)
                counter.inc(v)
        if s.lanes:
            lanes, s.lanes = s.lanes, {}
            for lane, (frames, nbytes) in lanes.items():
                key = self._lane_key(lane)
                reg.counter(f"{_LANE}{key}.tx.frames").inc(frames)
                reg.counter(f"{_LANE}{key}.tx.bytes").inc(nbytes)
        for h, hist in ((s.occupancy, self._h_occupancy),
                        (s.residency, self._h_residency)):
            if h.count:
                hist.merge_shard(h.count, h.sum, h.min, h.max,
                                 h.buckets)
                h.reset()
        self._c_records.inc(records)
        self._c_flushes.inc()

    def flush(self) -> None:
        """Drain every thread's shard into the registry (snapshot path;
        cross-thread, so a racing recorder may lose a rare increment —
        the documented registry-wide trade)."""
        with self._mu:
            shards = list(self._shards)
        for s in shards:
            if s.pending:
                self._flush_shard(s)

    # -- native plane ----------------------------------------------------

    def sync_native(self, stats: Optional[Dict[str, int]]) -> None:
        """Fold a native-core absolute-counter snapshot (the one-struct
        FFI call) into ``wire.native.*`` registry counters as deltas, so
        windowed rates and quantile math treat both planes alike."""
        if not stats:
            return
        last = self._native_last
        for field, suffix in _NATIVE_FIELDS:
            cur = int(stats.get(field, 0))
            prev = last.get(field, 0)
            if cur > prev:
                self._reg.counter(_NATIVE + suffix).inc(cur - prev)
            last[field] = cur


class _NullWireStats:
    """Shared no-op WireStats for ``PS_WIRE_TELEMETRY=0`` / disabled
    registries: one attribute call on a do-nothing method, no state."""

    enabled = False

    def tx_op(self, n: int = 1) -> None:
        pass

    def tx_msg(self, ops: int) -> None:
        pass

    def tx_frame(self, lane, zc_bytes, copy_bytes=0, frames=1) -> None:
        pass

    def tx_syscalls(self, n: int = 1) -> None:
        pass

    def rx_op(self, n: int = 1) -> None:
        pass

    def rx_frame(self, zc_bytes, copy_bytes=0, frames=1) -> None:
        pass

    def rx_msg(self, ops, zc_bytes, copy_bytes=0) -> None:
        pass

    def rx_syscalls(self, n: int = 1) -> None:
        pass

    def batch_occupancy(self, ops: int) -> None:
        pass

    def lane_residency(self, wait_s: float) -> None:
        pass

    def flush(self) -> None:
        pass

    def sync_native(self, stats) -> None:
        pass


NULL_WIRE = _NullWireStats()


def make_wire_stats(registry: Optional[Registry], env=None):
    """The van-side factory: a live :class:`WireStats` on an enabled
    registry with ``PS_WIRE_TELEMETRY`` unset/on, else :data:`NULL_WIRE`."""
    env = env if env is not None else environment.get()
    if registry is None or not getattr(registry, "enabled", False):
        return NULL_WIRE
    if not env.find_bool("PS_WIRE_TELEMETRY", True):
        return NULL_WIRE
    return WireStats(registry, env)
