"""Scheduler rank-assignment policies (reference: van.cc:112-265):
preferred ranks (aux_id), BYTEPS_ORDERED_HOSTS, and mixed mode."""

import itertools
import threading

from pslite_tpu.base import server_rank_to_id, worker_rank_to_id
from pslite_tpu.environment import Environment
from pslite_tpu.message import Role
from pslite_tpu.postoffice import Postoffice

_seq = itertools.count(60000)


def _cluster(num_workers, num_servers, per_node_env, base_extra=None):
    """Build scheduler+servers+workers with per-node env overrides.
    Policy vars the scheduler reads (BYTEPS_*) go in ``base_extra``."""
    port = next(_seq)
    base = {
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_NUM_SERVER": str(num_servers),
        "DMLC_PS_ROOT_URI": "lo",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NODE_HOST": "lo",
        "PS_VAN_TYPE": "loopback",
    }
    if base_extra:
        base.update(base_extra)
    nodes = []
    nodes.append(Postoffice(Role.SCHEDULER,
                            env=Environment(dict(base))))
    for i in range(num_servers):
        env = dict(base, **per_node_env("server", i))
        nodes.append(Postoffice(Role.SERVER, env=Environment(env)))
    for i in range(num_workers):
        env = dict(base, **per_node_env("worker", i))
        nodes.append(Postoffice(Role.WORKER, env=Environment(env)))
    threads = [threading.Thread(target=p.start, daemon=True) for p in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "cluster start timed out"
    return nodes


def _finalize(nodes):
    threads = [
        threading.Thread(target=p.finalize, daemon=True) for p in nodes
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)


def test_preferred_ranks_honored():
    """Every node supplies DMLC_RANK -> ids follow the preferences,
    regardless of registration order."""
    prefs = {"server": {0: 1, 1: 0}, "worker": {0: 1, 1: 0}}

    nodes = _cluster(
        2, 2, lambda role, i: {"DMLC_RANK": str(prefs[role][i])}
    )
    try:
        servers = [n for n in nodes if n.is_server]
        workers = [n for n in nodes if n.is_worker]
        # Construction order i was given preferred rank prefs[role][i].
        assert servers[0].van.my_node.id == server_rank_to_id(1)
        assert servers[1].van.my_node.id == server_rank_to_id(0)
        assert workers[0].van.my_node.id == worker_rank_to_id(1)
        assert workers[1].van.my_node.id == worker_rank_to_id(0)
    finally:
        _finalize(nodes)


def test_mixed_mode_prefers_non_colocated_servers():
    """BYTEPS_ENABLE_MIXED_MODE: servers NOT sharing a host with workers
    get the lowest server ranks (van.cc:126-150)."""
    # Two servers on distinct hosts; the worker shares "hostB".
    hosts = {"server": {0: "hostB", 1: "hostA"}, "worker": {0: "hostB"}}

    def env(role, i):
        return {"DMLC_NODE_HOST": hosts[role][i]}

    nodes = _cluster(1, 2, env,
                     base_extra={"BYTEPS_ENABLE_MIXED_MODE": "1"})
    try:
        servers = {n.van.my_node.hostname: n.van.my_node.id
                   for n in nodes if n.is_server}
        # hostA (not colocated with the worker) takes rank 0.
        assert servers["hostA"] == server_rank_to_id(0)
        assert servers["hostB"] == server_rank_to_id(1)
    finally:
        _finalize(nodes)


def test_ordered_hosts_policy():
    """BYTEPS_ORDERED_HOSTS pins rank order to the listed host order."""
    hosts = {"worker": {0: "h2", 1: "h1"}, "server": {0: "h1"}}

    def env(role, i):
        return {"DMLC_NODE_HOST": hosts[role][i]}

    nodes = _cluster(2, 1, env,
                     base_extra={"BYTEPS_ORDERED_HOSTS": "h1,h2"})
    try:
        workers = {n.van.my_node.hostname: n.van.my_node.id
                   for n in nodes if n.is_worker}
        assert workers["h1"] == worker_rank_to_id(0)
        assert workers["h2"] == worker_rank_to_id(1)
    finally:
        _finalize(nodes)
