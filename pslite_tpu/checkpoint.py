"""Checkpoint / resume of server state.

The reference has **no** checkpointing (SURVEY §5: server state lives only
in the user handler's memory) — this is the idiomatic TPU addition the
survey calls for: snapshot the sharded engine stores (dense buckets +
sparse tables) and message-path KVServer stores, restore them into a fresh
cluster.  Uses orbax when available, with a dependency-free ``.npz``
fallback so checkpoints work on any host.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np

from .utils import logging as log


def have_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def save_engine_orbax(engine, path: str, sparse_engine=None) -> None:
    """Orbax-backed snapshot in the FLEET-SIZE-PORTABLE v2 layout.

    Everything is saved as GLOBAL LOGICAL arrays — dense stores and
    vector optimizer states sliced to ``total_len`` (no shard padding),
    the adam step as one entry, sparse tables unpacked + de-interleaved
    to global row order — computed DEVICE-SIDE (store slices, jnp
    reshape/transpose chains: see SparseEngine.store_global_device), so
    multi-host saves never fetch non-addressable shards to host.  A
    checkpoint written by an 8-shard engine then restores into any
    shard count, closing the r04 gap where only the npz backend was
    elastic (VERDICT r04 weak #7): orbax restore reshards arrays onto
    the restoring fleet's own shardings.

    Optimizer kinds ride in the tree keys (``opt/<bucket>/k_<kind>``)
    so restore needs no side-channel metadata read.  A ``format_v2``
    marker distinguishes this layout from legacy physical-layout
    checkpoints, which :func:`restore_engine_orbax` still restores
    (same-fleet only, as before).
    """
    import orbax.checkpoint as ocp

    state = {
        "format_v2": np.full((1,), 2, np.int64),
        "dense": {},
        "opt": {},
        "sparse": {},
        "sparse_acc": {},
    }
    for name, bucket in engine._buckets.items():
        state["dense"][name] = engine.store_array(name)[: bucket.total_len]
        opt = engine.opt_state(name)
        if opt is not None:
            kind, states = opt
            slots = []
            for i, s in enumerate(states):
                if kind == "adam" and i == 2:
                    # Per-shard step counter -> one entry (identical on
                    # every shard by construction).
                    slots.append(s.reshape(-1)[:1])
                else:
                    slots.append(s[: bucket.total_len])
            state["opt"][name] = {f"k_{kind}": slots}
    if sparse_engine is not None:
        for name in sparse_engine._tables:
            state["sparse"][name] = sparse_engine.store_global_device(name)
            # ALWAYS save an accumulator (zeros when the table never saw
            # an adagrad push): the restore target can then be built from
            # registration alone, with no save/restore structure
            # mismatch either way.
            sparse_engine.ensure_acc(name)
            state["sparse_acc"][name] = sparse_engine.acc_global_device(
                name
            )
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), state, force=True)
        ckptr.wait_until_finished()


def _restore_orbax_v2(engine, path: str, sparse_engine, saved_md) -> None:
    """Restore a fleet-size-portable (v2) orbax checkpoint: targets are
    GLOBAL LOGICAL shapes carrying THIS engine's shardings — orbax
    reshards on read, so the saving fleet's shard count is irrelevant —
    and the setters convert logical -> physical layouts device-side."""
    import orbax.checkpoint as ocp

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh, axis = engine.mesh, engine.axis
    n_sh = engine.num_shards

    def _sds(shape, dtype, shard_dim0=True):
        # Logical (unpadded) sizes rarely divide the shard count evenly,
        # and NamedSharding requires even division — read such arrays
        # replicated (every host reads the full array; the setters
        # reshard to physical layouts device-side right after).
        even = shard_dim0 and shape[0] % n_sh == 0
        spec = (P(axis, *([None] * (len(shape) - 1)))
                if even else P(*([None] * len(shape))))
        return jax.ShapeDtypeStruct(
            tuple(shape), np.dtype(dtype),
            sharding=NamedSharding(mesh, spec),
        )

    target = {
        "format_v2": np.zeros((1,), np.int64),
        "dense": {},
        "opt": {},
        "sparse": {},
        "sparse_acc": {},
    }
    for name, bucket in engine._buckets.items():
        log.check(name in saved_md["dense"],
                  f"bucket {name!r} not in checkpoint")
        target["dense"][name] = _sds((bucket.total_len,), bucket.dtype)
    opt_kinds = {}
    for name, kinds in dict(saved_md["opt"]).items():
        (kkey, slots), = list(dict(kinds).items())
        kind = kkey[2:]  # "k_adam" -> "adam"
        opt_kinds[name] = kind
        tslots = []
        for i, m in enumerate(slots):
            repl = kind == "adam" and i == 2  # the step scalar
            tslots.append(_sds(
                tuple(m.shape),
                getattr(m, "dtype", np.float32),
                shard_dim0=not repl,
            ))
        target["opt"][name] = {kkey: tslots}
    if sparse_engine is not None:
        for name, t in sparse_engine._tables.items():
            log.check(name in saved_md["sparse"],
                      f"table {name!r} not in checkpoint")
            target["sparse"][name] = _sds((t.num_rows, t.dim), t.dtype)
            target["sparse_acc"][name] = _sds((t.num_rows,), np.float32)
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(os.path.abspath(path), target)
    for name, arr in state["dense"].items():
        engine.set_store_array(name, arr)
    for name, kinds in state["opt"].items():
        engine.set_opt_state(name, opt_kinds[name],
                             list(kinds[f"k_{opt_kinds[name]}"]))
    if sparse_engine is not None:
        for name, arr in state["sparse"].items():
            sparse_engine.set_store_array(name, arr, global_rows=True)
        for name, arr in state["sparse_acc"].items():
            sparse_engine.ensure_acc(name)
            sparse_engine.set_acc_array(name, arr, global_rows=True)


def restore_engine_orbax(engine, path: str, sparse_engine=None) -> None:
    """Restore an orbax snapshot; buckets/tables must be pre-registered so
    the target shardings exist (same contract as restore_engine).

    v2 checkpoints (format_v2 marker — global logical layouts) restore
    into ANY shard count; legacy checkpoints (raw physical layouts)
    restore same-fleet/same-layout only, as before."""
    import orbax.checkpoint as ocp

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        with ocp.StandardCheckpointer() as _mc:
            saved_md = _mc.metadata(os.path.abspath(path))
        saved_md = getattr(saved_md, "item_metadata", saved_md)
    except Exception as exc:  # noqa: BLE001 - metadata probe is best-effort
        # The probe decides v2 (fleet-portable global layout) vs legacy
        # (physical layout, same-fleet only).  When it fails we fall
        # into the legacy path BLIND — correct for real legacy
        # checkpoints, but a v2 checkpoint restored this way dies later
        # in opaque orbax shape errors.  Say so up front.
        saved_md = None
        log.warning(
            f"could not determine checkpoint format for {path!r} "
            f"(orbax metadata probe failed: {exc!r}); assuming the "
            f"LEGACY physical layout — if this checkpoint was saved in "
            f"the v2 fleet-portable layout, the restore below will "
            f"fail with shape/sharding errors"
        )
    if saved_md is not None:
        try:
            saved_md["format_v2"]  # KeyError on legacy checkpoints
            is_v2 = True
        except Exception:  # noqa: BLE001 - marker absent = legacy
            is_v2 = False
        if is_v2:
            _restore_orbax_v2(engine, path, sparse_engine, saved_md)
            return

    target = {"dense": {}, "sparse": {}, "sparse_acc": {}}
    for name in engine._buckets:
        target["dense"][name] = engine.store_spec(name)
    if sparse_engine is not None:
        # The saver's PHYSICAL table layout can differ from a fresh
        # registration's: demotion-era checkpoints (adagrad pushes used
        # to demote packed tables) hold unpacked stores.  Match the
        # restore target to the saved shape — if the checkpoint holds
        # the unpacked form of a currently-packed table, demote it
        # before targeting.
        for name in sparse_engine._tables:
            t = sparse_engine._tables[name]
            saved_shape = None
            if saved_md is not None:
                try:
                    saved_shape = tuple(saved_md["sparse"][name].shape)
                except Exception:  # noqa: BLE001
                    saved_shape = None
            unpacked = (
                t.rows_per_shard * sparse_engine.num_shards, t.dim
            )
            if t.pack > 1 and saved_shape == unpacked:
                # COMPAT: checkpoints from the demotion era (adagrad
                # pushes used to demote packed tables to the unpacked
                # layout) hold unpacked stores; demote the live table
                # so the restore target matches.
                with sparse_engine._table_mu[name]:
                    sparse_engine._ensure_unpacked(name)
            elif t.pack == 1 and saved_shape is not None \
                    and saved_shape != unpacked:
                # The inverse mismatch (a lane-packed save restored
                # into an unpacked-layout table) cannot be repaired
                # here; fail with the cause instead of an opaque orbax
                # shape error.
                raise log.CheckError(
                    f"orbax checkpoint for table {name!r} holds a "
                    f"different physical layout {saved_shape} than the "
                    f"live table's {unpacked} (different lane packing, "
                    f"shard count, or rows_per_shard) — orbax restores "
                    f"are same-fleet/same-layout; use the npz "
                    f"checkpoint path (fleet-portable global layout)"
                )
            target["sparse"][name] = sparse_engine.store_spec(name)
            # Mirror of save: every registered table has an acc entry in
            # the checkpoint, so target it unconditionally (no
            # ensure_acc pre-call needed by users).
            sparse_engine.ensure_acc(name)
            acc = sparse_engine._acc[name]
            target["sparse_acc"][name] = jax.ShapeDtypeStruct(
                acc.shape, acc.dtype,
                sharding=NamedSharding(
                    sparse_engine.mesh, P(sparse_engine.axis)
                ),
            )
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(os.path.abspath(path), target)
    # The targets are ShapeDtypeStructs carrying the live stores'
    # shardings (no allocation), so orbax hands back arrays already in
    # the target shardings; the setters assign them directly (no host
    # round-trip — multi-host arrays aren't host-fetchable).
    for name, arr in state["dense"].items():
        engine.set_store_array(name, arr)
    if sparse_engine is not None:
        for name, arr in state["sparse"].items():
            sparse_engine.set_store_array(name, arr)
        for name, arr in state.get("sparse_acc", {}).items():
            sparse_engine.set_acc_array(name, arr)


def save_engine(engine, path: str, sparse_engine=None) -> None:
    """Snapshot every dense bucket (and sparse table) to ``path``.

    FLEET-SIZE PORTABLE (format v2): everything is saved in GLOBAL
    logical layout — dense stores and vector optimizer states sliced to
    ``total_len`` (no shard padding), the adam step counter as a scalar,
    sparse tables and accumulators de-interleaved to global row order —
    so a checkpoint written by an 8-shard engine restores into a
    4-shard (or any-shard) engine: the elastic keepalive-restart story
    (save → exit 254 → restart with a different fleet → restore).
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    meta = {"version": 2, "dense": {}, "sparse": {}, "opt": {}}
    for name, bucket in engine._buckets.items():
        arrays[f"dense/{name}"] = np.asarray(
            engine.store_array(name)
        )[: bucket.total_len]
        meta["dense"][name] = {
            "keys": bucket.keys.tolist(),
            "val_len": bucket.val_len,
            "total_len": bucket.total_len,
        }
        opt = engine.opt_state(name)
        if opt is not None:
            kind, states = opt
            meta["opt"][name] = {"kind": kind, "n": len(states)}
            for i, s in enumerate(states):
                host = np.asarray(s)
                if kind == "adam" and i == 2:
                    # Per-shard step counter -> one scalar (identical on
                    # every shard by construction).
                    host = host.reshape(-1)[:1]
                else:
                    host = host[: bucket.total_len]
                arrays[f"opt/{name}/{i}"] = host
    if sparse_engine is not None:
        from .parallel.sparse import _deinterleave_rows

        for name, table in sparse_engine._tables.items():
            S, rps = sparse_engine.num_shards, table.rows_per_shard
            arrays[f"sparse/{name}"] = _deinterleave_rows(
                np.asarray(sparse_engine.store_array(name)),
                table.num_rows, rps, S,
            )
            meta["sparse"][name] = {
                "num_rows": table.num_rows,
                "dim": table.dim,
                "has_acc": name in sparse_engine._acc,
            }
            if name in sparse_engine._acc:
                arrays[f"sparse_acc/{name}"] = _deinterleave_rows(
                    np.asarray(sparse_engine.acc_array(name)),
                    table.num_rows, rps, S,
                )
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    np.savez(path, **arrays)


def restore_engine(engine, path: str, sparse_engine=None) -> None:
    """Restore buckets/tables saved by :func:`save_engine`.

    Buckets must already be registered (register_dense/register_sparse) so
    shapes, shardings, and compiled programs match — the same contract as
    the reference's first-touch registration.  The restoring engine's
    shard count may differ from the saver's (format v2 saves global
    layouts; see save_engine).  v1 checkpoints (pre-r04: padded dense
    stores, shard-interleaved tables) restore onto same-shard-count
    engines only.
    """
    if not path.endswith(".npz"):
        path = path + ".npz"
    data = np.load(path)
    meta = json.loads(bytes(data["__meta__"]).decode())
    v2 = meta.get("version", 1) >= 2
    for name in meta["dense"]:
        log.check(name in engine._buckets,
                  f"bucket {name!r} not registered before restore")
        engine.set_store_array(name, data[f"dense/{name}"])
    for name, info in meta.get("opt", {}).items():
        engine.set_opt_state(
            name, info["kind"],
            [data[f"opt/{name}/{i}"] for i in range(info["n"])],
        )
    if sparse_engine is not None:
        for name, info in meta["sparse"].items():
            sparse_engine.set_store_array(
                name, data[f"sparse/{name}"], global_rows=v2
            )
            if info.get("has_acc"):
                sparse_engine.set_acc_array(
                    name, data[f"sparse_acc/{name}"], global_rows=v2
                )


class AsyncEngineCheckpointer:
    """Non-blocking engine checkpoints: the device-side snapshot happens
    at call time (``store_array``'s copy under the bucket lock — cheap,
    async-dispatched), while the host fetch and file write run on a
    background thread so the training loop never blocks on IO.

    The snapshot is consistent as of the ``save()`` call: pushes applied
    after ``save()`` returns are NOT in the checkpoint, exactly like a
    synchronous save at that point.  ``wait()`` joins all pending writes
    (call before shutdown); a failed write surfaces on the next
    ``save()``/``wait()`` as an exception.
    """

    def __init__(self, max_pending: int = 2):
        import queue
        import threading

        self._q = queue.Queue(maxsize=max_pending)
        self._errors = []
        self._mu = threading.Lock()
        self._worker = threading.Thread(
            target=self._run, name="async-ckpt", daemon=True
        )
        self._worker.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            arrays, meta, path = item
            try:
                host = {k: np.asarray(v) for k, v in arrays.items()}
                host["__meta__"] = np.frombuffer(
                    json.dumps(meta).encode(), dtype=np.uint8
                )
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                tmp = path + ".tmp"
                np.savez(tmp, **host)
                # np.savez appends .npz to the filename it writes.
                os.replace(
                    tmp if tmp.endswith(".npz") else tmp + ".npz",
                    path if path.endswith(".npz") else path + ".npz",
                )
            except Exception as exc:  # noqa: BLE001 - surfaced to caller
                with self._mu:
                    self._errors.append(exc)
            finally:
                self._q.task_done()

    def _raise_pending_error(self):
        with self._mu:
            if self._errors:
                raise self._errors.pop(0)

    def save(self, engine, path: str, sparse_engine=None) -> None:
        """Queue a snapshot of the engine (same layout as
        :func:`save_engine`); blocks only if ``max_pending`` writes are
        already in flight (back-pressure, not data loss)."""
        self._raise_pending_error()
        arrays = {}
        meta = {"dense": {}, "sparse": {}, "opt": {}}
        for name, bucket in engine._buckets.items():
            arrays[f"dense/{name}"] = engine.store_array(name)
            meta["dense"][name] = {
                "keys": bucket.keys.tolist(),
                "val_len": bucket.val_len,
                "total_len": bucket.total_len,
            }
            opt = engine.opt_state(name)
            if opt is not None:
                kind, states = opt
                meta["opt"][name] = {"kind": kind, "n": len(states)}
                for i, s in enumerate(states):
                    arrays[f"opt/{name}/{i}"] = s
        if sparse_engine is not None:
            for name, table in sparse_engine._tables.items():
                arrays[f"sparse/{name}"] = sparse_engine.store_array(name)
                meta["sparse"][name] = {
                    "num_rows": table.num_rows,
                    "dim": table.dim,
                    "has_acc": name in sparse_engine._acc,
                }
                if name in sparse_engine._acc:
                    arrays[f"sparse_acc/{name}"] = (
                        sparse_engine.acc_array(name)
                    )
        self._q.put((arrays, meta, path))

    def wait(self) -> None:
        """Block until every queued checkpoint is on disk; re-raise the
        first background failure if one occurred."""
        self._q.join()
        self._raise_pending_error()

    def close(self) -> None:
        self.wait()
        self._q.put(None)
        self._worker.join()


def save_range_segment(path: str, keys: np.ndarray, vals: np.ndarray,
                       lens: Optional[np.ndarray],
                       fmt: str = "npz") -> str:
    """Write one exported key range (the ``export_range`` currency:
    sorted keys, flat vals, per-key lens) as a snapshot segment file —
    the storage half of the coordinated-snapshot plane
    (kv/snapshot.py, docs/durability.md).  ``fmt="orbax"`` uses orbax
    when importable and falls back to the dependency-free ``.npz``
    layout otherwise; returns the format actually written (the
    manifest records it so restore needs no probing)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if fmt == "orbax" and have_orbax():
        import orbax.checkpoint as ocp

        state = {"keys": np.asarray(keys), "vals": np.asarray(vals)}
        if lens is not None:
            state["lens"] = np.asarray(lens, dtype=np.int64)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.abspath(path), state, force=True)
            ckptr.wait_until_finished()
        return "orbax"
    if fmt == "orbax":
        log.warning("PS_SNAPSHOT_FORMAT=orbax but orbax is not "
                    "importable; writing the npz fallback")
    arrays = {"keys": np.asarray(keys), "vals": np.asarray(vals)}
    if lens is not None:
        arrays["lens"] = np.asarray(lens, dtype=np.int64)
    # Atomic AND durable: a kill mid-write must leave either the old
    # segment or none, never a torn file a later restore would die
    # decoding — and the bytes must be ON DISK before the caller
    # reports success (the scheduler commits the manifest and prunes
    # the previous snapshot on our say-so; a power loss after an
    # un-fsynced "success" would leave zero usable restore points).
    tmp = f"{path}.tmp.{os.getpid()}"
    np.savez(tmp, **arrays)
    with open(tmp + ".npz", "rb") as fh:
        os.fsync(fh.fileno())
    os.replace(tmp + ".npz", path + ".npz")
    fsync_dir(os.path.dirname(path) or ".")
    return "npz"


def fsync_dir(directory: str) -> None:
    """Best-effort directory-entry durability after a rename (some
    filesystems don't support fsync on a directory fd)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def load_range_segment(path: str, fmt: str = "npz"):
    """Inverse of :func:`save_range_segment`; returns
    ``(keys, vals, lens|None)``."""
    if fmt == "orbax":
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            state = ckptr.restore(os.path.abspath(path))
        keys = np.asarray(state["keys"])
        vals = np.asarray(state["vals"])
        lens = (np.asarray(state["lens"])
                if "lens" in state else None)
        return keys, vals, lens
    data = np.load(path + ".npz")
    return (data["keys"], data["vals"],
            data["lens"] if "lens" in data.files else None)


def save_kv_store(store: Dict[int, np.ndarray], path: str) -> None:
    """Snapshot a message-path server store (e.g. KVServerDefaultHandle)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **{str(k): v for k, v in store.items()})


def load_kv_store(path: str) -> Dict[int, np.ndarray]:
    if not path.endswith(".npz"):
        path = path + ".npz"
    data = np.load(path)
    return {int(k): data[k] for k in data.files}


def save_server_handle(handle, path: str) -> None:
    """Snapshot a message-path server handle — params AND optimizer
    state, so a keepalive-restarted server (tracker/local.py exit-254
    elasticity) resumes async-PS training exactly where it died.

    Supports ``KVServerDefaultHandle`` (store only) and
    ``KVServerOptimizerHandle`` (store + momentum/adam slots + step
    counts).  The reference has no server persistence at all (its
    server state dies with the handler's memory — SURVEY §5)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # list() snapshots guard against apply threads inserting first-seen
    # keys mid-iteration.  Handles now apply IN PLACE (no per-push
    # reallocation — kv_app.py / docs/apply_shards.md), so a key being
    # updated while it is copied below may capture a mid-update value;
    # for a consistent snapshot (and bitwise-exact multi-slot state,
    # e.g. adam m/v of one in-flight key), quiesce the server (stop
    # pushing / drain) before saving.
    arrays = {f"s_{k}": v for k, v in list(handle.store.items())}
    for slot in ("_m", "_v"):
        for k, v in list(getattr(handle, slot, {}).items()):
            arrays[f"{slot}_{k}"] = v
    t = getattr(handle, "_t", None)
    if t:
        items = sorted(list(t.items()))
        arrays["t_keys"] = np.asarray([k for k, _ in items], np.int64)
        arrays["t_vals"] = np.asarray([v for _, v in items], np.int64)
    np.savez(path, **arrays)


def load_server_handle(handle, path: str) -> None:
    """Restore state saved by :func:`save_server_handle` into a freshly
    constructed handle (hyperparameters come from the constructor)."""
    if not path.endswith(".npz"):
        path = path + ".npz"
    data = np.load(path)
    t_map = {}
    if "t_keys" in data.files:
        t_map = dict(
            zip(data["t_keys"].tolist(), data["t_vals"].tolist())
        )
    for name in data.files:
        if name.startswith("s_"):
            handle.store[int(name[2:])] = data[name]
        elif name.startswith("_m_"):
            handle._m[int(name[3:])] = data[name]
        elif name.startswith("_v_"):
            handle._v[int(name[3:])] = data[name]
    if t_map and hasattr(handle, "_t"):
        handle._t.update(t_map)


def save_train_state(flat_store, step: int, path: str) -> str:
    """Snapshot the flagship training loop's sharded parameter store.

    Returns the path actually written (np.savez appends ``.npz``)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, store=np.asarray(flat_store), step=np.int64(step))
    return path if path.endswith(".npz") else path + ".npz"


def load_train_state(path: str, sharding=None):
    if not path.endswith(".npz"):
        path = path + ".npz"
    data = np.load(path)
    store = data["store"]
    if sharding is not None:
        import jax

        store = jax.device_put(store, sharding)
    return store, int(data["step"])
