"""Cluster-wide telemetry (docs/observability.md): metrics registry,
distributed tracing, and the scheduler-pulled METRICS_PULL plane."""

import glob
import json
import os
import sys

import numpy as np
import pytest

from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.telemetry.metrics import Histogram, Registry

from helpers import LoopbackCluster

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


# -- metrics primitives ------------------------------------------------------


def test_histogram_bucket_math():
    h = Histogram("lat", lo=1e-6)
    # Bucket 0 holds everything <= lo; bucket i covers
    # [lo*2^(i-1), lo*2^i).
    assert h.bucket_index(0.0) == 0
    assert h.bucket_index(1e-6) == 0
    assert h.bucket_index(1.5e-6) == 1
    assert h.bucket_index(3e-6) == 2
    assert h.bucket_index(1e13) == Histogram.NBUCKETS - 1  # clamped
    for v in (1e-6, 2e-6, 4e-6, 1e-3, 1e-3, 1e-3):
        h.observe(v)
    assert h.count == 6
    assert h.min == 1e-6 and h.max == 1e-3
    assert abs(h.sum - (7e-6 + 3e-3)) < 1e-12
    # Quantiles are monotone, bounded by observed extremes, and p50 of
    # this set lands in the 1e-3 mass.
    p50, p90, p99 = h.quantile(0.5), h.quantile(0.9), h.quantile(0.99)
    assert h.min <= p50 <= p90 <= p99 <= h.max
    # Half the mass sits at 1e-3: the upper quantiles must find it.
    assert p90 > 1e-4
    snap = h.snapshot()
    assert snap["count"] == 6
    assert sum(n for _i, n in snap["buckets"]) == 6


def test_registry_snapshot_and_reset():
    reg = Registry()
    reg.counter("a").inc(3)
    reg.gauge("g").set(7.5)
    reg.histogram("h").observe(0.5)
    reg.topk("t").add(42, 5)
    reg.topk("t").add(7, 1)
    snap = reg.snapshot()
    assert snap["counters"]["a"] == 3
    assert snap["gauges"]["g"] == 7.5
    assert snap["histograms"]["h"]["count"] == 1
    assert snap["topk"]["t"][0] == [42, 5]
    assert snap["uptime_s"] >= 0
    json.dumps(snap)  # the METRICS_PULL body contract
    # Idempotent get-or-create; type collisions fail loud.
    assert reg.counter("a") is reg.counter("a")
    with pytest.raises(TypeError):
        reg.gauge("a")
    reg.reset()
    snap = reg.snapshot()
    assert snap["counters"]["a"] == 0
    assert snap["histograms"]["h"]["count"] == 0
    assert snap["topk"]["t"] == []


def test_disabled_registry_is_null():
    reg = Registry(enabled=False)
    c = reg.counter("x")
    c.inc(100)
    assert c.value == 0
    reg.histogram("h").observe(1.0)
    assert reg.snapshot()["counters"] == {}
    # All disabled instruments are the same shared singleton.
    assert reg.counter("y") is c


def test_topk_bounded_eviction():
    reg = Registry()
    t = reg.topk("hot", cap=4)
    for k in range(4):
        t.add(k, 10 * (k + 1))
    t.add(99, 1)  # evicts the min (key 0, count 10), inherits its count
    top = dict(t.top(10))
    assert 0 not in top
    assert top[99] == 11


def test_wire_trace_extension_roundtrip():
    """meta.trace rides a tagged tail block: roundtrips when set, adds
    zero bytes when unset, and decoders skip unknown tags by length."""
    from pslite_tpu import wire
    from pslite_tpu.message import Meta

    m = Meta(timestamp=7, sender=9, recver=8, request=True, push=True)
    plain = wire.pack_meta(m)
    m.trace = 0xDEADBEEFCAFE
    traced = wire.pack_meta(m)
    assert len(traced) > len(plain)
    out = wire.unpack_meta(traced)
    assert out.trace == 0xDEADBEEFCAFE and out.timestamp == 7
    assert wire.unpack_meta(plain).trace == 0
    # Unknown trailing tag (tag=200, len=4): skipped, trace still read.
    import struct

    exotic = traced + struct.pack("<BB4s", 200, 4, b"abcd")
    assert wire.unpack_meta(exotic).trace == 0xDEADBEEFCAFE


# -- live-cluster storm fixtures ---------------------------------------------


def _run_storm(cluster, rounds=5, keys=None):
    servers = []
    for po in cluster.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(KVServerDefaultHandle())
        servers.append(s)
    workers = [KVWorker(0, 0, postoffice=po) for po in cluster.workers]
    if keys is None:
        keys = np.array([3, 2 ** 63 + 9], dtype=np.uint64)
    vals = np.ones(len(keys) * 16, dtype=np.float32)
    for _ in range(rounds):
        tss = [w.push(keys, vals) for w in workers]
        for w, ts in zip(workers, tss):
            w.wait(ts)
    out = np.zeros_like(vals)
    workers[0].wait(workers[0].pull(keys, out))
    return servers, workers, out


# -- METRICS_PULL pull plane -------------------------------------------------


def test_metrics_pull_returns_all_nodes():
    cluster = LoopbackCluster(num_workers=2, num_servers=2)
    cluster.start()
    servers, workers = [], []
    try:
        servers, workers, _out = _run_storm(cluster)
        snap = cluster.scheduler.collect_cluster_metrics(timeout_s=10)
        ids = {po.van.my_node.id for po in cluster.all_nodes()}
        assert set(snap.keys()) == ids  # every registered node answered
        roles = sorted(s["role"] for s in snap.values())
        assert roles == ["scheduler", "server", "server", "worker",
                         "worker"]
        wsnap = next(s for s in snap.values() if s["role"] == "worker")
        m = wsnap["metrics"]
        assert m["counters"]["kv.pushes"] >= 5
        assert m["histograms"]["kv.push_latency_s"]["count"] >= 5
        assert m["histograms"]["kv.push_latency_s"]["p99"] > 0
        assert "van.lane_depth" in m["gauges"]
        ssnap = next(s for s in snap.values() if s["role"] == "server")
        sm = ssnap["metrics"]
        assert sm["counters"]["kv.server_push_requests"] >= 5
        assert sm["topk"]["kv.hot_keys"], "hot-key tracker empty"
        # A second pull works (token machinery resets cleanly).
        snap2 = cluster.scheduler.collect_cluster_metrics(timeout_s=10)
        assert set(snap2.keys()) == ids
        for w in workers:
            w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_psmon_table_against_live_cluster():
    """Acceptance: psmon against a live 2w+2s cluster prints per-node
    rows with request-latency, lane depth, apply throughput, and
    retransmit columns."""
    import psmon

    cluster = LoopbackCluster(num_workers=2, num_servers=2)
    cluster.start()
    servers, workers = [], []
    try:
        servers, workers, _out = _run_storm(cluster, rounds=8)
        snap = psmon.collect(cluster.scheduler, timeout_s=10)
        table = psmon.format_table(snap)
        for col in ("req_p50ms", "lane_q", "xfers", "apply/s", "retx",
                    "repl_fwd", "per-role rollup", "hot keys"):
            assert col in table, table
        # One row per node.
        for po in cluster.all_nodes():
            assert f"\n{po.van.my_node.id:>5} " in "\n" + table, table
        js = json.loads(psmon.to_json(snap))
        assert len(js) == 5
        for w in workers:
            w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


# -- distributed tracing -----------------------------------------------------


def test_trace_propagation_and_chrome_export(tmp_path):
    """A sampled push's spans share one trace id across worker and
    server processes; the per-node export is valid Chrome trace JSON
    whose request span nests its wire_send."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=2,
        env_extra={"PS_TRACE_SAMPLE": "1",
                   "PS_TRACE_DIR": str(tmp_path)},
    )
    cluster.start()
    servers, workers = [], []
    try:
        servers, workers, _out = _run_storm(cluster, rounds=3)
        for w in workers:
            w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()
    files = sorted(glob.glob(str(tmp_path / "pslite_trace_*.json")))
    worker_files = [f for f in files if "worker" in f]
    server_files = [f for f in files if "server" in f]
    assert worker_files and server_files, files
    wdoc = json.load(open(worker_files[0]))
    events = wdoc["traceEvents"]
    assert all("ph" in e for e in events)  # valid shape
    assert all("ts" in e for e in events if e["ph"] != "M")
    # Pick a trace id that produced a request span on the worker.
    req = next(e for e in events
               if e["name"] == "request" and e["args"].get("trace"))
    tid = req["args"]["trace"]
    wire = [e for e in events if e["name"] == "wire_send"
            and e["args"].get("trace") == tid]
    assert wire, "request trace has no wire_send span"
    # Nesting: the request span encloses its wire sends.
    for e in wire:
        assert req["ts"] <= e["ts"] + 1.0
        assert e["ts"] + e["dur"] <= req["ts"] + req["dur"] + 1.0
    # The SAME id shows up server-side as an apply span (the key 3
    # slice lands on rank 0; check both server files).
    server_hits = []
    for f in server_files:
        sev = json.load(open(f))["traceEvents"]
        server_hits += [e for e in sev if e["args"].get("trace") == tid
                        and e["name"] == "apply"]
    assert server_hits, "worker trace id never reached a server apply"
    # Worker-side completion closes the loop.
    assert any(e["name"] == "complete" and e["args"].get("trace") == tid
               for e in events)


def test_trace_sample_zero_records_nothing(tmp_path):
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={"PS_TRACE_DIR": str(tmp_path)},  # sampling off
    )
    cluster.start()
    servers, workers = [], []
    try:
        servers, workers, _out = _run_storm(cluster, rounds=2,
                                            keys=np.array([3], np.uint64))
        assert cluster.workers[0].tracer.num_events == 0
        for w in workers:
            w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()
    assert not glob.glob(str(tmp_path / "pslite_trace_*.json"))


# -- counter migration (one idiom, thin legacy views) ------------------------


def test_legacy_counter_views_ride_the_registry():
    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    servers, workers = [], []
    try:
        servers, workers, _out = _run_storm(cluster, rounds=3,
                                            keys=np.array([3], np.uint64))
        srv_po = cluster.servers[0]
        pool = servers[0]._apply_pool
        if pool is not None:
            # The legacy attribute and the registry counter are one.
            assert pool.sharded_requests == srv_po.metrics.counter(
                "apply.sharded_requests").value
            assert pool.sharded_requests >= 3
        for w in workers:
            w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_chaos_drop_shows_as_retransmit_delta():
    """A chaos-van receive drop is healed by PS_RESEND and visible as a
    resender.retransmits counter delta on the sending side."""
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="chaos+loopback",
        env_extra={
            "PS_CHAOS": "seed=5,drop=0.3",
            "PS_RESEND": "1",
            "PS_RESEND_TIMEOUT": "50",
        },
    )
    cluster.start()
    servers, workers = [], []
    try:
        servers, workers, out = _run_storm(cluster, rounds=6,
                                           keys=np.array([3], np.uint64))
        np.testing.assert_allclose(out, 6 * np.ones_like(out))
        dropped = sum(
            po.van.chaos_stats["recv_dropped"]
            for po in cluster.all_nodes()
        )
        assert dropped > 0, "chaos injected nothing"
        retx = sum(
            po.metrics.counter("resender.retransmits").value
            for po in cluster.all_nodes()
        )
        assert retx > 0, "drops never surfaced as retransmit counters"
        # chaos_stats itself is a registry view now (one counter idiom).
        van = cluster.workers[0].van
        assert van.chaos_stats["send_dropped"] == van.metrics.counter(
            "chaos.send_dropped").value
        for w in workers:
            w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()


def test_heartbeat_gap_histogram():
    cluster = LoopbackCluster(
        num_workers=1, num_servers=1,
        env_extra={"PS_HEARTBEAT_INTERVAL": "0.05",
                   "PS_HEARTBEAT_TIMEOUT": "60"},
    )
    cluster.start()
    servers, workers = [], []
    try:
        import time

        time.sleep(0.4)
        h = cluster.scheduler.metrics.histogram("heartbeat.gap_s",
                                                lo=1e-3)
        assert h.count >= 2
        assert 0.01 < h.quantile(0.5) < 2.0
        servers, workers, _out = _run_storm(cluster, rounds=1,
                                            keys=np.array([3], np.uint64))
        for w in workers:
            w.stop()
    finally:
        for s in servers:
            s.stop()
        cluster.finalize()
