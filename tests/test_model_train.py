"""Flagship model: forward shapes, and the PS-integrated SPMD training step
on a (dp=4, sp=2) virtual mesh — loss must decrease on learnable toy data."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from pslite_tpu.models.train import make_ps_train_step, toy_batch
from pslite_tpu.models.transformer import ModelConfig, forward, init_params
from pslite_tpu.parallel.mesh import make_mesh


def test_forward_shapes_single_device():
    cfg = ModelConfig(vocab=64, dim=32, heads=2, layers=1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, 64)
    assert np.isfinite(np.asarray(logits)).all()


def test_ps_train_step_loss_decreases():
    cfg = ModelConfig(vocab=32, dim=32, heads=2, layers=1)
    mesh = make_mesh((4, 2), ("dp", "sp"))
    step, store, tok_sharding, _ = make_ps_train_step(cfg, mesh, lr=0.5)

    inputs, targets = toy_batch(cfg, batch=8, seq=16)
    inputs = jax.device_put(inputs, tok_sharding)
    targets = jax.device_put(targets, tok_sharding)

    losses = []
    for _ in range(10):
        store, loss = step(store, inputs, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.9, losses


def test_train_step_ulysses_strategy():
    """sp_strategy='ulysses' trains to the same kind of loss as ring (same
    sharded layout, interchangeable attention)."""
    import jax
    import numpy as np

    from pslite_tpu.models.train import make_ps_train_step, toy_batch
    from pslite_tpu.models.transformer import ModelConfig
    from pslite_tpu.parallel.mesh import make_mesh

    cfg = ModelConfig(vocab=64, dim=32, heads=4, layers=1)
    mesh = make_mesh((2, 4), ("dp", "sp"))
    losses = {}
    for strategy in ("ring", "ulysses"):
        step, store, tok_sharding, _ = make_ps_train_step(
            cfg, mesh, lr=0.1, sp_strategy=strategy
        )
        inputs, targets = toy_batch(cfg, batch=2, seq=32)
        inputs = jax.device_put(inputs, tok_sharding)
        targets = jax.device_put(targets, tok_sharding)
        store, loss = step(store, inputs, targets)
        losses[strategy] = float(loss)
        assert np.isfinite(losses[strategy])
    # Same math, different communication schedule: losses must agree.
    np.testing.assert_allclose(losses["ring"], losses["ulysses"],
                               rtol=1e-4, atol=1e-5)


def test_train_step_remat_matches():
    """cfg.remat trades FLOPs for activation memory without changing the
    math: losses match the non-remat config."""
    import jax
    import numpy as np

    from pslite_tpu.models.train import make_ps_train_step, toy_batch
    from pslite_tpu.models.transformer import ModelConfig
    from pslite_tpu.parallel.mesh import make_mesh

    mesh = make_mesh((2, 4), ("dp", "sp"))
    losses = {}
    for remat in (False, True):
        cfg = ModelConfig(vocab=64, dim=32, heads=2, layers=2, remat=remat)
        step, store, tok_sharding, _ = make_ps_train_step(cfg, mesh, lr=0.1)
        inputs, targets = toy_batch(cfg, batch=2, seq=16)
        inputs = jax.device_put(inputs, tok_sharding)
        targets = jax.device_put(targets, tok_sharding)
        # TWO steps: the step-2 loss depends on step-1's GRADIENTS (the
        # store update), which is exactly what remat recomputes — a
        # single-step loss would be a pre-update tautology.
        store, _ = step(store, inputs, targets)
        store, loss2 = step(store, inputs, targets)
        losses[remat] = float(loss2)
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)
