"""Chaos-injection wrapper van (``PS_VAN_TYPE=chaos[+inner]``).

Generalizes the single-knob ``PS_DROP_MSG`` receive-side drop injector
(reference: van.cc:652-658) into a full fault harness: a seeded spec
(``PS_CHAOS``) injects drops, delays, reorders, duplicates, one-way
partitions, and crash-at-phase hooks into ANY underlying transport.
This is the harness the fault-tolerance tier (failure detector, request
deadlines, replication — docs/fault_tolerance.md) is proven against.

Spec grammar (comma-separated ``key=value``)::

    PS_CHAOS="seed=42,drop=0.2,delay=1:20,reorder=0.1,dup=0.05,
              part=9>8,crash=recv:50"

    seed=N        RNG seed (mixed with the node id once assigned, so
                  every node draws a distinct but reproducible stream)
    drop=P        receive-side drop probability (0..1)
    send_drop=P   send-side drop probability
    delay=A[:B]   receive-side delay, uniform in [A, B] milliseconds
    send_delay=A[:B]   same, applied on the send path
    reorder=P     hold a message back and deliver its successor first
    dup=P         deliver a message twice
    part=A>B[;C>D]     one-way partition: traffic from node A to node B
                  silently vanishes (evaluated on both endpoints)
    crash=PHASE:N  after N data messages through PHASE, the node "goes
                  dark" in that direction and stops heartbeating, so
                  the failure detector declares it dead:
                    recv — deaf: swallows further incoming data, still
                           sends (in-flight applies drain)
                    send — mute: black-holes outgoing data, still
                           receives
                    dead — both directions dark

Injection applies to DATA messages only, and only after bootstrap
(``van.ready``): the control plane (ADD_NODE, barriers, ACKs) stays
healthy so scenarios model data-plane faults, not a broken rendezvous —
with the one exception that a crashed node suppresses its outgoing
HEARTBEATs (that is what makes the detector notice).  Reorder holds at
most one message and releases it behind the next arrival; under low
traffic pair it with ``PS_RESEND`` so a held tail message is healed by
retransmit.  Per-van counters live in ``van.chaos_stats``; the crash
hook sets ``van.chaos_crashed`` (a ``threading.Event``) so tests can
synchronize on the exact kill moment.
"""

from __future__ import annotations

import collections
import copy
import random
import threading
import time
from typing import Dict, Optional, Tuple

from ..message import Command, Message
from ..utils import logging as log


def _parse_prob(val: str) -> float:
    p = float(val)
    log.check(0.0 <= p <= 1.0, f"chaos probability out of range: {val}")
    return p


def _parse_ms_range(val: str) -> Tuple[float, float]:
    """``"5"`` / ``"5ms"`` / ``"1:20"`` -> (lo_s, hi_s)."""
    parts = val.split(":")
    log.check(len(parts) in (1, 2), f"bad chaos delay spec: {val}")
    nums = [float(p.strip().removesuffix("ms")) / 1000.0 for p in parts]
    lo = nums[0]
    hi = nums[1] if len(nums) == 2 else nums[0]
    log.check(0 <= lo <= hi, f"bad chaos delay range: {val}")
    return lo, hi


def parse_spec(spec: str) -> dict:
    """Parse a ``PS_CHAOS`` spec string into a plain dict (exposed for
    tests and for the docs' grammar to stay honest)."""
    out: dict = {
        "seed": 0, "drop": 0.0, "send_drop": 0.0,
        "delay": (0.0, 0.0), "send_delay": (0.0, 0.0),
        "reorder": 0.0, "dup": 0.0,
        "partitions": set(), "crash_phase": None, "crash_after": 0,
    }
    for field in spec.split(","):
        field = field.strip()
        if not field:
            continue
        log.check("=" in field, f"bad chaos field (want key=value): {field}")
        key, val = field.split("=", 1)
        key, val = key.strip(), val.strip()
        if key == "seed":
            out["seed"] = int(val)
        elif key in ("drop", "send_drop", "reorder", "dup"):
            out[key] = _parse_prob(val)
        elif key in ("delay", "send_delay"):
            out[key] = _parse_ms_range(val)
        elif key == "part":
            for edge in val.split(";"):
                a, b = edge.split(">")
                out["partitions"].add((int(a), int(b)))
        elif key == "crash":
            phase, n = val.split(":")
            log.check(phase in ("recv", "send", "dead"),
                      f"unknown chaos crash phase: {phase}")
            out["crash_phase"] = phase
            out["crash_after"] = int(n)
        else:
            log.check(False, f"unknown chaos spec key: {key}")
    return out


class ChaosPolicy:
    """Per-van decision engine over a parsed spec.  All randomness
    comes from one seeded stream (seed mixed with the node id once
    assigned), guarded by a lock — the recv pump and every per-peer
    send-lane thread draw from it.  Decisions are reproducible given
    the same seed AND the same message interleaving; with concurrent
    lanes the interleaving itself varies, so treat replay determinism
    as per-thread-schedule, not absolute."""

    def __init__(self, spec: str):
        self.spec = parse_spec(spec)
        self._rng: Optional[random.Random] = None
        self._rng_node = None
        self._rng_mu = threading.Lock()
        self._counts: collections.Counter = collections.Counter()
        self._mu = threading.Lock()
        self.crashed = threading.Event()

    def _roll_locked(self, node_id: int) -> random.Random:
        if self._rng is None or self._rng_node != node_id:
            # Knuth-style mix so nodes sharing one spec draw distinct
            # (but individually reproducible) streams.
            self._rng = random.Random(
                self.spec["seed"] ^ (node_id * 2654435761)
            )
            self._rng_node = node_id
        return self._rng

    def partitioned(self, sender: int, recver: int) -> bool:
        return (sender, recver) in self.spec["partitions"]

    def count_data(self, phase: str) -> None:
        """Advance the crash counter for one data message through
        ``phase``; trips the crash once the budget is spent."""
        want = self.spec["crash_phase"]
        if want is None or self.crashed.is_set():
            return
        if want != phase and want != "dead":
            return
        with self._mu:
            self._counts[want] += 1
            if self._counts[want] > self.spec["crash_after"]:
                self.crashed.set()

    def crash_blocks(self, phase: str) -> bool:
        if not self.crashed.is_set():
            return False
        want = self.spec["crash_phase"]
        return want == "dead" or want == phase

    def draw(self, node_id: int, kind: str) -> bool:
        p = self.spec[kind]
        if p <= 0:
            return False
        with self._rng_mu:
            return self._roll_locked(node_id).random() < p

    def delay_s(self, node_id: int, kind: str) -> float:
        lo, hi = self.spec[kind]
        if hi <= 0:
            return 0.0
        with self._rng_mu:
            return self._roll_locked(node_id).uniform(lo, hi)


class _ChaosStats:
    """Counter-style view over the node registry's ``chaos.*`` counters
    (one counter idiom everywhere — docs/observability.md).  Keeps the
    historical ``van.chaos_stats`` read surface: ``stats["recv_dropped"]``,
    ``stats.values()``, ``stats.items()``; unseen keys read 0.  When the
    registry is disabled (PS_TELEMETRY=0) a private enabled registry
    backs the view, so chaos accounting keeps working untelemetered."""

    _PREFIX = "chaos."

    def __init__(self, registry):
        from ..telemetry.metrics import node_registry

        self._registry = node_registry(registry)

    def inc(self, key: str, n: int = 1) -> None:
        self._registry.counter(self._PREFIX + key).inc(n)

    def __getitem__(self, key: str) -> int:
        return self._registry.counter(self._PREFIX + key).value

    def get(self, key: str, default: int = 0) -> int:
        # dict.get semantics: the default applies only to counters that
        # were never created — a present counter returns its value even
        # when that value is 0.
        name = self._PREFIX + key
        vals = self._registry.counters_with_prefix(self._PREFIX)
        return vals.get(name, default)

    def items(self):
        return {
            name[len(self._PREFIX):]: v
            for name, v in self._registry.counters_with_prefix(
                self._PREFIX
            ).items()
        }.items()

    def keys(self):
        return [k for k, _ in self.items()]

    def values(self):
        return [v for _, v in self.items()]


_CLASS_CACHE: Dict[type, type] = {}


def chaos_class(inner_cls: type) -> type:
    """Subclass ``inner_cls`` with chaos injection wrapped around its
    ``send_msg`` / ``recv_msg`` (cached: one class per transport)."""
    cached = _CLASS_CACHE.get(inner_cls)
    if cached is not None:
        return cached

    class ChaosVan(inner_cls):  # type: ignore[misc, valid-type]
        def __init__(self, postoffice):
            super().__init__(postoffice)
            self.chaos = ChaosPolicy(self.env.find("PS_CHAOS") or "")
            self.chaos_stats = _ChaosStats(self.metrics)
            if getattr(self, "_native", None) is not None:
                # Chaos drop/delay/dup operate on DELIVERED messages;
                # native reassembly would collapse a whole transfer
                # into one delivery and change per-chunk fault
                # semantics — keep the Python assembler in the loop.
                self._native.set_reassembly(False)
            # Reorder holdback + redelivery queue: only the (single)
            # receive-loop thread touches these.
            self._chaos_held: Optional[Message] = None
            self._chaos_requeued: collections.deque = collections.deque()

        @property
        def chaos_crashed(self) -> threading.Event:
            return self.chaos.crashed

        # -- send path ---------------------------------------------------

        def _native_submit(self, msg: Message):
            """Chaos injection wraps ``send_msg``; the native sender
            lanes would transmit around it, silently disabling every
            send-side fault — chaos vans always take the Python path
            (ISSUE 6: chaos van unchanged)."""
            return None

        def send_msg(self, msg: Message) -> int:
            chaos = self.chaos
            ctrl = msg.meta.control
            if not self.ready.is_set():
                return super().send_msg(msg)
            if not ctrl.empty():
                if (chaos.crashed.is_set()
                        and ctrl.cmd == Command.HEARTBEAT):
                    # A crashed node stops heartbeating — this is the
                    # signal the failure detector keys on.
                    self.chaos_stats.inc("heartbeat_suppressed")
                    return 0
                if (chaos.crash_blocks("send")
                        and ctrl.cmd != Command.TERMINATE
                        and chaos.spec["crash_phase"] == "dead"):
                    self.chaos_stats.inc("send_blackholed")
                    return 0
                return super().send_msg(msg)
            me = self.my_node.id
            chaos.count_data("send")
            if chaos.crash_blocks("send"):
                self.chaos_stats.inc("send_blackholed")
                return 0
            if chaos.partitioned(me, msg.meta.recver):
                self.chaos_stats.inc("send_partitioned")
                return 0
            if chaos.draw(me, "send_drop"):
                self.chaos_stats.inc("send_dropped")
                return 0
            d = chaos.delay_s(me, "send_delay")
            if d > 0:
                # Sleeping here only stalls this peer's lane thread —
                # per-peer lanes keep the other destinations flowing.
                self.chaos_stats.inc("send_delayed")
                time.sleep(d)
            return super().send_msg(msg)

        # -- receive path ------------------------------------------------

        def _chaos_dup(self, msg: Message) -> Message:
            dup = Message()
            dup.meta = copy.deepcopy(msg.meta)
            dup.data = list(msg.data)
            return dup

        def _chaos_release(self, msg: Message) -> Message:
            """Deliver ``msg``; a held (reordered) predecessor rides the
            redelivery queue so it arrives right behind it."""
            if self._chaos_held is not None:
                held, self._chaos_held = self._chaos_held, None
                self._chaos_requeued.append(held)
            return msg

        def recv_msg(self) -> Optional[Message]:
            if self._chaos_requeued:
                return self._chaos_requeued.popleft()
            chaos = self.chaos
            while True:
                msg = super().recv_msg()
                if msg is None:
                    return None
                if not self.ready.is_set() or not msg.meta.control.empty():
                    if (msg.meta.control.cmd != Command.TERMINATE
                            and chaos.crash_blocks("recv")
                            and chaos.spec["crash_phase"] == "dead"):
                        self.chaos_stats.inc("recv_swallowed")
                        continue
                    return self._chaos_release(msg)
                me = self.my_node.id
                chaos.count_data("recv")
                if chaos.crash_blocks("recv"):
                    self.chaos_stats.inc("recv_swallowed")
                    continue
                if chaos.partitioned(msg.meta.sender, me):
                    self.chaos_stats.inc("recv_partitioned")
                    continue
                if chaos.draw(me, "drop"):
                    self.chaos_stats.inc("recv_dropped")
                    continue
                d = chaos.delay_s(me, "delay")
                if d > 0:
                    self.chaos_stats.inc("recv_delayed")
                    time.sleep(d)
                if self._chaos_held is None and chaos.draw(me, "reorder"):
                    # Hold this one back; its successor passes it.
                    self.chaos_stats.inc("reordered")
                    self._chaos_held = msg
                    continue
                if chaos.draw(me, "dup"):
                    self.chaos_stats.inc("duplicated")
                    self._chaos_requeued.append(self._chaos_dup(msg))
                return self._chaos_release(msg)

    ChaosVan.__name__ = f"Chaos{inner_cls.__name__}"
    ChaosVan.__qualname__ = ChaosVan.__name__
    _CLASS_CACHE[inner_cls] = ChaosVan
    return ChaosVan


def _inner_class(name: str) -> type:
    from . import transport_class

    if name.startswith("ici") or name == "xla":
        # The ICI data plane rides XLA collectives, not the
        # send_msg/recv_msg hooks chaos wraps.
        raise ValueError(f"chaos van cannot wrap inner type {name!r}")
    cls = transport_class(name)
    if cls is None:
        raise ValueError(f"chaos van cannot wrap inner type {name!r}")
    return cls


def create_chaos(inner: str, postoffice):
    return chaos_class(_inner_class(inner))(postoffice)
