"""Elastic restart ACROSS FLEET SIZES: checkpoint -> exit 254 -> restore
into a smaller fleet -> continue training.

The reference's keepalive launcher restarts any child that exits 254
(tracker/dmlc_local.py:16-25) but its recovery re-admits the SAME
roster; this framework closes the loop for a fleet whose size changed
across the restart: format-v2 checkpoints save GLOBAL logical state
(checkpoint.save_engine), so an 8-shard save restores into a 4-shard
engine — stores, fused-optimizer state (adam), and sparse tables with
row-Adagrad accumulators all carry over, verified here against a host
recurrence of the full uninterrupted run.

Run (the launcher supplies the keepalive):

    python -m pslite_tpu.tracker.local -n 0 -s 0 -- \
        python examples/elastic_restart.py

First incarnation: 8-shard engine, 2 training steps, save, exit 254.
Second incarnation (checkpoint exists): 4-shard engine on HALF the
devices, restore, 2 more steps, verify, print ELASTIC_RESTART_OK.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

LR, B1, B2, EPS = 1e-2, 0.9, 0.999, 1e-8
SLR, SEPS = 0.1, 1e-8
TOTAL = 100          # 1 key x 100 values: padding differs per fleet size
ROWS, DIM = 13, 4
STEPS = 4            # 2 before the restart, 2 after


def _grads(step: int) -> np.ndarray:
    return np.random.default_rng(100 + step).normal(
        size=TOTAL
    ).astype(np.float32)


def _row_grads(step: int) -> tuple:
    rng = np.random.default_rng(200 + step)
    idx = rng.integers(0, ROWS, size=6).astype(np.int32)
    g = rng.normal(size=(6, DIM)).astype(np.float32)
    return idx, g


def _host_model():
    """The uninterrupted 4-step run as a host recurrence (adam with
    bias correction exactly as ops/quantize's fused handle applies it,
    row-adagrad as parallel/sparse._adagrad_rows)."""
    store = np.zeros(TOTAL, np.float64)
    m = np.zeros(TOTAL, np.float64)
    v = np.zeros(TOTAL, np.float64)
    table = np.zeros((ROWS, DIM), np.float64)
    acc = np.zeros(ROWS, np.float64)
    for step in range(1, STEPS + 1):
        g = _grads(step - 1).astype(np.float64)
        m = B1 * m + (1 - B1) * g
        v = B2 * v + (1 - B2) * g * g
        alpha = LR * np.sqrt(1 - B2 ** step) / (1 - B1 ** step)
        store = store - alpha * m / (np.sqrt(v) + EPS)
        idx, rg = _row_grads(step - 1)
        G = np.zeros((ROWS, DIM), np.float64)
        np.add.at(G, idx, rg.astype(np.float64))
        acc = acc + np.mean(G ** 2, axis=1)
        table = table - SLR * G / (np.sqrt(acc)[:, None] + SEPS)
    return store, table


def _build(mesh):
    from pslite_tpu.parallel.engine import CollectiveEngine
    from pslite_tpu.parallel.sparse import SparseEngine

    eng = CollectiveEngine(mesh=mesh, server_handle=f"adam:{LR}")
    se = SparseEngine(mesh)
    eng.register_dense("w", np.arange(1, dtype=np.uint64), TOTAL)
    se.register_sparse("emb", ROWS, DIM)
    return eng, se


def _train(eng, se, steps) -> None:
    W = eng.num_shards
    for step in steps:
        g = _grads(step)
        eng.push_pull("w", np.tile(g / W, (W, 1)))
        idx, rg = _row_grads(step)
        # Worker 0 carries the batch; the rest push empty rows.
        idxs = np.zeros((W, len(idx)), np.int32)
        gs = np.zeros((W, len(idx), DIM), np.float32)
        idxs[0], gs[0] = idx, rg
        se.push("emb", idxs, gs, handle=f"row_adagrad:{SLR},{SEPS}")
        se.block("emb")


def main() -> int:
    if os.environ.get("DMLC_ROLE", "scheduler") != "scheduler":
        return 0

    import jax

    from pslite_tpu import checkpoint
    from jax.sharding import Mesh

    ckpt = os.environ.get("PS_CKPT", "/tmp/pslite_elastic_restart_ck")
    # Both fleet-portable backends drive the same loop (PS_CKPT_BACKEND
    # = npz | orbax): orbax saves a directory, npz a file.
    backend = os.environ.get("PS_CKPT_BACKEND", "npz")
    if backend == "orbax":
        ck_exists = os.path.isdir(ckpt)
        save = checkpoint.save_engine_orbax
        restore = checkpoint.restore_engine_orbax
    else:
        ck_exists = os.path.exists(ckpt + ".npz")
        save = checkpoint.save_engine
        restore = checkpoint.restore_engine
    devs = jax.devices()
    if not ck_exists:
        # FIRST incarnation: the full 8-shard fleet, half the run.
        eng, se = _build(Mesh(np.array(devs), ("kv",)))
        _train(eng, se, range(0, 2))
        save(eng, ckpt, sparse_engine=se)
        print(f"saved 2-step checkpoint from {eng.num_shards} shards; "
              f"exiting 254 for the keepalive restart", flush=True)
        return 254
    # SECOND incarnation: HALF the fleet (4 shards), restore, finish.
    eng, se = _build(Mesh(np.array(devs[: len(devs) // 2]), ("kv",)))
    restore(eng, ckpt, sparse_engine=se)
    _train(eng, se, range(2, STEPS))
    store, table = _host_model()
    got = np.asarray(eng.pull("w"))
    np.testing.assert_allclose(got, store, rtol=1e-4, atol=1e-4)
    all_rows = np.tile(np.arange(ROWS, dtype=np.int32),
                       (eng.num_shards, 1))
    got_t = np.asarray(se.pull("emb", all_rows))[0]
    np.testing.assert_allclose(got_t, table, rtol=1e-4, atol=1e-4)
    print(f"ELASTIC_RESTART_OK restored onto {eng.num_shards} shards, "
          f"training matches the uninterrupted run", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
