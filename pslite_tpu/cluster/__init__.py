"""Cluster-level control policies (docs/autopilot.md).

The scheduler already owns every sense (ClusterHistory, SLO watchdog,
trace attribution) and every actuator (routing epochs, elastic
join/decommission, snapshots, apply retune); this package holds the
policies that connect them without an operator in the loop.
"""

from .autopilot import Autopilot, Veto, parse_mode  # noqa: F401
