"""KV push/pull basics — the ps-lite "hello world", any van.

Run a 2-worker cluster on one machine::

    python -m pslite_tpu.tracker.local -n 2 -s 2 -- python examples/kv_basics.py
    python -m pslite_tpu.tracker.local -n 2 -s 2 --van shm -- python examples/kv_basics.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import pslite_tpu as ps


def main() -> None:
    role = os.environ.get("DMLC_ROLE")
    if role is None:
        sys.exit(
            "DMLC_ROLE not set — run this under the launcher:\n"
            "  python -m pslite_tpu.tracker.local -n 2 -s 2 -- "
            "python examples/kv_basics.py"
        )
    ps.start_ps()

    server = None
    if role in ("server", "joint"):
        server = ps.KVServer(0)
        server.set_request_handle(ps.KVServerDefaultHandle())

    if role in ("worker", "joint"):
        po = ps.postoffice(ps.Role.WORKER)
        kv = ps.KVWorker(0, 0)

        # One key per server, 1024 floats each.
        ranges = po.get_server_key_ranges()
        keys = np.sort(
            np.array([r.begin + 1 for r in ranges], dtype=np.uint64)
        )
        grads = np.full(len(keys) * 1024, 1.0, dtype=np.float32)

        ts = kv.push(keys, grads)          # async; returns a timestamp
        kv.wait(ts)                        # ZPush/Wait semantics
        po.barrier(0, ps.WORKER_GROUP)     # all workers pushed

        params = np.zeros_like(grads)
        kv.wait(kv.pull(keys, params))     # aggregated across workers
        expected = float(po.num_workers)
        print(f"worker {po.my_rank()}: pulled {params[0]} "
              f"(expected {expected})")
        assert np.allclose(params, expected)

        # Wire-compressed push for bandwidth-limited links:
        kv.wait(kv.push(keys, grads, compress="int8"))

    ps.finalize()
    if server is not None:
        server.stop()


if __name__ == "__main__":
    main()
