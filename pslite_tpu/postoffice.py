"""Postoffice — per-role-instance center of the system.

Capability parity with the reference's ``include/ps/internal/postoffice.h`` /
``src/postoffice.cc``: env parsing, van creation, node-id bookkeeping and
group membership tables, barriers, server key ranges, the heartbeat registry,
the customer registry (with the 5 s readiness wait), and lifecycle
(start/finalize).  One Postoffice exists per role *instance*; instance groups
(``DMLC_GROUP_SIZE``) and the JOINT role put several in one process
(reference: ps.h:59-138, postoffice.cc:20-43).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from . import environment, vans
from .base import (
    ALL_GROUP,
    EMPTY_ID,
    MAX_KEY,
    SCHEDULER_GROUP,
    SCHEDULER_ID,
    SERVER_GROUP,
    WORKER_GROUP,
    group_members,
    id_to_rank,
    is_server_id,
    is_worker_id,
    server_rank_to_id,
    worker_rank_to_id,
)
from .message import Command, Control, Message, Node, Role
from .range import Range
from .telemetry.flight import FlightRecorder
from .telemetry.metrics import Registry
from .telemetry.tracing import Tracer
from .utils import logging as log


class Postoffice:
    def __init__(
        self,
        role: Role,
        instance_idx: int = 0,
        env: Optional[environment.Environment] = None,
    ):
        log.check(role in (Role.WORKER, Role.SERVER, Role.SCHEDULER),
                  "JOINT is expanded by start_ps, not hosted by one Postoffice")
        self.env = env or environment.get()
        self.role = role
        self.instance_idx = instance_idx
        self.num_workers = self.env.find_int("DMLC_NUM_WORKER", 0)
        self.num_servers = self.env.find_int("DMLC_NUM_SERVER", 0)
        self.group_size = max(self.env.find_int("DMLC_GROUP_SIZE", 1), 1)
        self.verbose = self.env.find_int("PS_VERBOSE", 0)
        log.set_verbosity(self.verbose)
        self._preferred_group_rank = self.env.find_int("DMLC_RANK", EMPTY_ID)

        self._customers: Dict[tuple, object] = {}
        self._pending_msgs: Dict[tuple, list] = {}
        self._customers_cv = threading.Condition()
        self._barrier_mu = threading.Lock()
        self._barrier_cv = threading.Condition(self._barrier_mu)
        self._barrier_done = False
        self._heartbeats: Dict[int, float] = {}
        self._heartbeat_mu = threading.Lock()
        self._start_time = time.time()
        # Failure/recovery hooks (docs/fault_tolerance.md): apps register
        # callbacks to learn when the failure detector declares a peer
        # dead (down=True) or a recovered replacement rejoins
        # (down=False).  KVWorker uses this to fail over key ranges.
        self._node_failure_hooks: List[Callable[[int, bool], None]] = []
        self._node_failure_mu = threading.Lock()
        self._exit_callback: Optional[Callable[[], None]] = None
        self._server_key_ranges: List[Range] = []
        self._server_key_ranges_mu = threading.Lock()
        # Elastic membership (docs/elasticity.md): with PS_ELASTIC=1 the
        # scheduler maintains a versioned RoutingTable (epoch-stamped
        # key-range assignment) broadcast on every membership change;
        # every node applies it here.  None = static routing (the
        # uniform split below) — the default cluster is byte-identical
        # to pre-elastic builds.
        self.elastic = self.env.find_int("PS_ELASTIC", 0) != 0
        # Set when the scheduler admitted this node as a live JOINER
        # (ELASTIC_JOIN_OPT on the roster): it skips the startup
        # barrier like a recovered node but must NOT run the replica
        # restore — its state arrives through range migration instead.
        self.elastic_join = False
        self._routing = None  # Optional[routing.RoutingTable]
        self._routing_mu = threading.Lock()
        self._routing_hooks: List[Callable[[object], None]] = []
        self._routing_hook_mu = threading.Lock()
        # Scheduler-side migration ledger: {(epoch, begin): wall the
        # epoch shipped}.  Entries clear when the new owner reports the
        # handoff landed (MIGRATE_DONE_OPT on ROUTING) — the snapshot
        # coordinator defers/vetoes cuts while any remain, so a
        # Command.SNAPSHOT broadcast can never slice a range
        # mid-handoff (docs/autopilot.md, docs/durability.md).
        self._migrations_pending: Dict[tuple, float] = {}
        self._migration_settle_s = self.env.find_float(
            "PS_MIGRATION_SETTLE_S", 120.0)
        # Live server group ranks (None = the static 0..num_servers-1).
        # Rank holes are legal after an out-of-order decommission.
        self._active_server_ranks: Optional[List[int]] = None
        # Graceful decommission handshake (request_decommission):
        # completed by the scheduler's REMOVE_NODE ack.
        self._removed_event = threading.Event()
        self._node_ids: Dict[int, List[int]] = {}
        self._build_node_id_table()

        # Per-NODE telemetry (docs/observability.md): one metrics
        # registry + one tracer + one fault flight recorder per
        # Postoffice — per-node even when many logical nodes share a
        # test process.  Created BEFORE the van so transports can
        # instrument from __init__.
        self.metrics = Registry(
            enabled=self.env.find_bool("PS_TELEMETRY", True)
        )
        self.tracer = Tracer(self.env, self.role_str(),
                             metrics=self.metrics)
        self.flight = FlightRecorder(self.env, self.role_str())
        # METRICS_PULL collection state (scheduler side).  _collect_mu
        # serializes whole pulls: the ClusterHistory sampler thread and
        # psmon/--serve scrape threads may pull concurrently, and an
        # unserialized second pull would bump the token mid-collection,
        # discarding the first caller's in-flight replies as stale (a
        # truncated snapshot reads as stale nodes → false node_stale
        # watchdog events on a healthy cluster).
        self._collect_mu = threading.Lock()
        self._metrics_cv = threading.Condition()
        self._metrics_token = 0
        self._metrics_replies: Dict[int, dict] = {}
        self._metrics_last_seen: Dict[int, float] = {}
        # TRACE_PULL collection state (docs/observability.md): same
        # broadcast+gather shape as METRICS_PULL, serialized under the
        # SAME collect lock (a trace pull racing a metrics pull is
        # fine — they use separate tokens/reply maps — but two trace
        # pulls must not interleave).  The collector itself (span
        # assembly, TTL retirement) is lazily built scheduler-side.
        self._trace_token = 0
        self._trace_replies: Dict[int, dict] = {}
        self._trace_collector = None  # telemetry.TraceCollector
        # Coordinated snapshot plane (docs/durability.md): scheduler-
        # side gather state (same token-gated shape as METRICS_PULL)
        # and the server-side hook registry (a KVServer registers to
        # receive SNAPSHOT control requests routed off the van pump).
        self._snapshot_mu = threading.Lock()
        self._snapshot_token = 0
        self._snapshot_replies: Dict[int, dict] = {}
        self._snapshot_hooks: List[Callable[[Message], bool]] = []
        self.snapshot_dir = self.env.find("PS_SNAPSHOT_DIR") or None
        # Continuous telemetry plane (docs/observability.md): the
        # scheduler's ClusterHistory sampler + SLO watchdog.  Lazily
        # built by start_history(); started automatically by start()
        # when PS_METRICS_INTERVAL > 0.
        self.history = None  # Optional[telemetry.ClusterHistory]

        van_type = self.env.find("PS_VAN_TYPE") or self.env.find(
            "DMLC_ENABLE_RDMA"
        ) or "tcp"
        self.van = vans.create(van_type, self)

    # -- role & rank ---------------------------------------------------------

    @property
    def is_worker(self) -> bool:
        return self.role == Role.WORKER

    @property
    def is_server(self) -> bool:
        return self.role == Role.SERVER

    @property
    def is_scheduler(self) -> bool:
        return self.role == Role.SCHEDULER

    def role_str(self) -> str:
        return self.role.name.lower()

    @property
    def num_worker_instances(self) -> int:
        return self.num_workers * self.group_size

    @property
    def num_server_instances(self) -> int:
        return self.num_servers * self.group_size

    @property
    def active_server_ranks(self) -> Optional[List[int]]:
        """Live server group ranks under elastic membership (None =
        the static ``0..num_servers-1``)."""
        return self._active_server_ranks

    @property
    def num_active_servers(self) -> int:
        """Count of LIVE server groups — differs from ``num_servers``
        only under elastic membership with rank holes."""
        if self._active_server_ranks is not None:
            return len(self._active_server_ranks)
        return self.num_servers

    @property
    def num_active_server_instances(self) -> int:
        return self.num_active_servers * self.group_size

    @property
    def preferred_rank(self) -> int:
        """Preferred *instance* rank sent in ADD_NODE aux_id (DMLC_RANK)."""
        if self._preferred_group_rank == EMPTY_ID:
            return EMPTY_ID
        return self._preferred_group_rank * self.group_size + self.instance_idx

    def my_rank(self) -> int:
        """My instance rank within my role."""
        return id_to_rank(self.van.my_node.id)

    def my_group_rank(self) -> int:
        return self.my_rank() // self.group_size

    def id_to_group_rank(self, node_id: int) -> int:
        """Group rank of any node id; scheduler maps to -1."""
        if node_id == SCHEDULER_ID:
            return -1
        return id_to_rank(node_id) // self.group_size

    def instance_rank_to_id(self, role: Role, instance_rank: int) -> int:
        if role == Role.WORKER:
            return worker_rank_to_id(instance_rank)
        return server_rank_to_id(instance_rank)

    @property
    def is_recovery(self) -> bool:
        return self.van.my_node.is_recovery

    def on_id_assigned(self, node: Node) -> None:
        self.tracer.node_id = node.id
        self.flight.node_id = node.id
        log.vlog(1, f"assigned id {node.id} (rank {id_to_rank(node.id)}) to me")

    # -- group membership ----------------------------------------------------

    def _build_node_id_table(self) -> None:
        """Group bitmask -> member instance ids (reference:
        postoffice.cc:115-137).  Under elastic membership the server
        side follows the routing table's ACTIVE ranks (joiners appear,
        departed ranks vanish — barriers, broadcasts, and the failure
        detector's expectations all read this table)."""
        worker_ids = [
            worker_rank_to_id(i) for i in range(self.num_worker_instances)
        ]
        if self._active_server_ranks is not None:
            server_ids = [
                server_rank_to_id(r * self.group_size + i)
                for r in self._active_server_ranks
                for i in range(self.group_size)
            ]
        else:
            server_ids = [
                server_rank_to_id(i)
                for i in range(self.num_server_instances)
            ]
        for group in range(1, 8):
            sched, srv, wrk = group_members(group)
            ids: List[int] = []
            if sched:
                ids.append(SCHEDULER_ID)
            if srv:
                ids.extend(server_ids)
            if wrk:
                ids.extend(worker_ids)
            self._node_ids[group] = ids

    def get_node_ids(self, group_or_id: int) -> List[int]:
        if group_or_id in self._node_ids:
            return self._node_ids[group_or_id]
        return [group_or_id]

    # -- lifecycle -----------------------------------------------------------

    def start(self, customer_id: int = 0, do_barrier: bool = True) -> None:
        self._start_time = time.time()
        self.van.start(customer_id)
        # A recovered node must not block on the startup barrier: the
        # original cohort passed it long ago (reference: van.cc:292-332).
        if do_barrier and not self.van.my_node.is_recovery:
            self.barrier(customer_id, ALL_GROUP, instance=True)
        # Continuous telemetry (docs/observability.md): the scheduler's
        # background METRICS_PULL sampler, default off.
        if (self.is_scheduler
                and self.env.find_float("PS_METRICS_INTERVAL", 0.0) > 0):
            self.start_history()
        log.vlog(1, f"{self.role_str()}[{self.instance_idx}] started")

    def finalize(self, customer_id: int = 0, do_barrier: bool = True) -> None:
        if do_barrier:
            self.barrier(customer_id, ALL_GROUP, instance=True)
        if customer_id == 0:
            self.van.stop()
            # Stop any still-registered customers: their receive threads
            # otherwise outlive the node and retain the whole
            # Postoffice→van→buffer graph (a long-lived host process
            # cycling clusters would accumulate one thread + its pinned
            # segments per app the caller forgot to stop —
            # postoffice.cc:159-176 equivalent teardown).
            with self._customers_cv:
                leftover = list(self._customers.values())
            for cust in leftover:
                cust.stop()
            if self._exit_callback is not None:
                self._exit_callback()

    def register_exit_callback(self, cb: Callable[[], None]) -> None:
        self._exit_callback = cb

    # -- barriers ------------------------------------------------------------

    def barrier(
        self, customer_id: int, group: int = ALL_GROUP,
        instance: bool = False, timeout_s: Optional[float] = None,
    ) -> None:
        """Block until every member of ``group`` reaches the barrier
        (reference: postoffice.cc:224-250).

        ``timeout_s`` bounds the wait (None = forever, the reference
        default): a member that died before reaching the barrier would
        otherwise wedge every peer.  On expiry raises CheckError; the
        caller must treat the cluster as degraded — a late release for
        THIS barrier may still arrive, so no further barrier should be
        issued until recovery re-establishes the roster."""
        members = self.get_node_ids(group)
        if len(members) <= 1:
            return
        with self._barrier_cv:
            self._barrier_done = False
        self.van.request_barrier(group, instance)
        with self._barrier_cv:
            ok = self._barrier_cv.wait_for(
                lambda: self._barrier_done, timeout_s
            )
        if not ok:
            # Withdraw the pending request so the stale count cannot
            # release a FUTURE barrier early for the surviving peers
            # (best-effort: a release already in flight wins the race,
            # in which case the peers passed and only this caller
            # treats the sync as failed — still safe, still degraded;
            # an unreachable scheduler must not mask the timeout
            # diagnostic below).
            try:
                self.van.cancel_barrier(group, instance)
            except Exception:  # noqa: BLE001 - best-effort withdrawal
                pass
        log.check(ok, f"barrier(group={group}) timed out after "
                      f"{timeout_s}s — peer dead before the barrier?")

    def manage(self, msg: Message) -> None:
        """Handle barrier responses (reference: postoffice.cc:270-283)."""
        if msg.meta.control.cmd in (Command.BARRIER, Command.INSTANCE_BARRIER):
            if not msg.meta.request:
                with self._barrier_cv:
                    self._barrier_done = True
                    self._barrier_cv.notify_all()

    # -- key ranges ----------------------------------------------------------

    def get_server_key_ranges(self) -> List[Range]:
        """Key-range partition over server groups: the current routing
        table's entries when elastic membership is live (one range per
        ENTRY — entries outnumber servers after a merge), else the
        static uniform split (reference: postoffice.cc:257-268)."""
        rt = self.current_routing()
        if rt is not None:
            return [Range(e.begin, e.end) for e in rt.entries]
        with self._server_key_ranges_mu:
            if not self._server_key_ranges:
                log.check(self.num_servers > 0, "no servers configured")
                span = MAX_KEY // self.num_servers
                for i in range(self.num_servers):
                    begin = span * i
                    end = span * (i + 1) if i + 1 < self.num_servers else MAX_KEY
                    self._server_key_ranges.append(Range(begin, end))
            return self._server_key_ranges

    def server_key_ranges_of(self, rank: int) -> List[Range]:
        """Every key range a server group rank currently owns (one
        under static routing; possibly several under elastic)."""
        rt = self.current_routing()
        if rt is not None:
            return rt.ranges_of(rank)
        ranges = self.get_server_key_ranges()
        return [ranges[rank]] if 0 <= rank < len(ranges) else []

    # -- elastic routing (docs/elasticity.md) --------------------------------

    def current_routing(self):
        """The routing table this node currently holds (None = static)."""
        with self._routing_mu:
            return self._routing

    def routing_table(self):
        """Like :meth:`current_routing`, but the elastic SCHEDULER
        lazily builds the epoch-0 table (identical to the static
        split) so membership changes always have a base to derive
        from."""
        with self._routing_mu:
            if self._routing is None and self.elastic and self.is_scheduler:
                from .routing import RoutingTable

                self._routing = RoutingTable.initial(self.num_servers)
            return self._routing

    def apply_routing(self, table) -> bool:
        """Adopt a (strictly newer) routing table: update membership-
        derived state (server count, active ranks, node-id tables) and
        run the routing hooks.  Returns False for stale epochs —
        reordered broadcasts can never roll routing backwards."""
        with self._routing_mu:
            cur = self._routing
            if cur is not None and table.epoch <= cur.epoch:
                return False
            self._routing = table
            if self.is_scheduler:
                # New epochs derive from a SETTLED base, so pending
                # entries of older epochs are superseded wholesale.
                now = time.time()
                self._migrations_pending = {
                    (table.epoch, e.begin): now
                    for e in table.migrations()
                }
        membership_changed = (
            table.num_servers != self.num_servers
            or self._active_server_ranks != list(table.active)
        )
        if membership_changed:
            self.num_servers = table.num_servers
            self._active_server_ranks = list(table.active)
            self._build_node_id_table()
            # Departed servers must not linger as perpetual STALE rows
            # in psmon (metrics_last_seen feeds its last-seen ages).
            live = set(table.active) | set(table.leaving)
            with self._metrics_cv:
                for nid in list(self._metrics_last_seen):
                    if (is_server_id(nid)
                            and id_to_rank(nid) // self.group_size
                            not in live):
                        del self._metrics_last_seen[nid]
        log.vlog(1, f"routing epoch {table.epoch}: active="
                    f"{list(table.active)} leaving={list(table.leaving)} "
                    f"entries={len(table.entries)}")
        # Flight recorder (docs/observability.md): membership changes
        # are the context every fault postmortem needs first.
        self.flight.record(
            "epoch_change", severity="info", epoch=table.epoch,
            active=list(table.active), leaving=list(table.leaving),
        )
        with self._routing_hook_mu:
            hooks = list(self._routing_hooks)
        for hook in hooks:
            try:
                hook(table)
            except Exception as exc:  # noqa: BLE001 - isolate hooks
                log.warning(f"routing hook failed: {exc!r}")
        return True

    def register_routing_hook(self, hook: Callable[[object], None]) -> None:
        """``hook(table)`` runs on every adopted routing epoch (van
        receive pump — keep it fast, never block on the van).  If a
        table is already live it is replayed immediately so a late-
        constructed app (a joiner's KVServer) sees the current epoch."""
        with self._routing_hook_mu:
            self._routing_hooks.append(hook)
        table = self.current_routing()
        if table is not None:
            try:
                hook(table)
            except Exception as exc:  # noqa: BLE001
                log.warning(f"routing hook failed on replay: {exc!r}")

    def unregister_routing_hook(self, hook) -> None:
        with self._routing_hook_mu:
            try:
                self._routing_hooks.remove(hook)
            except ValueError:
                pass

    def note_migration_done(self, epoch: int, begin: int) -> None:
        """Scheduler: a range handoff landed (the new owner's
        MIGRATE_DONE_OPT notification, or the replica-fallback unpark).
        Clears the snapshot coordinator's defer/veto reason."""
        with self._routing_mu:
            if self._migrations_pending.pop((epoch, begin), None) is None:
                return
            left = len(self._migrations_pending)
        log.vlog(1, f"migration of [{begin}, ...) @epoch {epoch} "
                    f"settled ({left} still in flight)")

    def migrations_in_flight(self) -> List[tuple]:
        """``(epoch, begin)`` of every range handoff the scheduler has
        shipped but not yet seen land.  Entries older than
        ``PS_MIGRATION_SETTLE_S`` expire with a warning — a lost
        notification must not wedge snapshots forever (the server-side
        fence still vetoes a cut that really is mid-handoff)."""
        now = time.time()
        expired = []
        with self._routing_mu:
            for key, t0 in list(self._migrations_pending.items()):
                if now - t0 > self._migration_settle_s:
                    del self._migrations_pending[key]
                    expired.append(key)
            pending = list(self._migrations_pending)
        for epoch, begin in expired:
            log.warning(f"migration of [{begin}, ...) @epoch {epoch} "
                        f"unreported for {self._migration_settle_s:.0f}s"
                        f"; assuming settled")
            self.flight.record("migration_expired", severity="warn",
                               epoch=epoch, begin=begin)
        return pending

    def request_decommission(self, timeout_s: float = 60.0) -> None:
        """Gracefully leave the running cluster (docs/elasticity.md):
        ask the scheduler to reassign this server's key ranges, wait
        for the migration + retirement handshake to complete.  After
        this returns, finalize with ``do_barrier=False`` — a retired
        node is no longer counted in any barrier."""
        log.check(self.is_server, "only servers decommission")
        log.check(self.elastic, "decommission requires PS_ELASTIC=1")
        self._removed_event.clear()
        msg = Message()
        msg.meta.recver = SCHEDULER_ID
        msg.meta.request = True
        msg.meta.body = json.dumps({"rank": self.my_group_rank()}).encode()
        msg.meta.control = Control(cmd=Command.REMOVE_NODE)
        msg.meta.timestamp = self.van.next_timestamp()
        self.van.send(msg)
        ok = self._removed_event.wait(timeout_s)
        log.check(ok, f"decommission did not complete in {timeout_s}s")

    def hot_key_hint(self) -> Dict[int, int]:
        """Scheduler-side load hint for load-weighted range splits:
        the union of ``kv.hot_keys`` top-k estimates from the most
        recent METRICS_PULL replies (psmon keeps these warm); empty
        when no snapshot was ever collected — splits then fall back to
        the widest range."""
        with self._metrics_cv:
            replies = dict(self._metrics_replies)
        hint: Dict[int, int] = {}
        for snap in replies.values():
            top = (snap.get("metrics", {}) or {}).get(
                "topk", {}).get("kv.hot_keys") or []
            for item in top:
                try:
                    k, n = int(item[0]), int(item[1])
                except (TypeError, ValueError, IndexError):
                    continue
                hint[k] = hint.get(k, 0) + n
        return hint

    # -- customers -----------------------------------------------------------

    _MAX_PENDING_PER_APP = 10000

    def add_customer(self, customer) -> None:
        # Registration and the flush of parked messages happen atomically
        # under the same lock that buffer_pending serializes on, so a
        # concurrently arriving message can never be delivered ahead of the
        # parked ones (accept() only enqueues; it takes no locks of ours).
        with self._customers_cv:
            key = (customer.app_id, customer.customer_id)
            log.check(key not in self._customers, f"customer {key} exists")
            for msg in self._pending_msgs.pop(key, []):
                customer.accept(msg)
            self._customers[key] = customer
            self._customers_cv.notify_all()

    def buffer_pending(self, app_id: int, customer_id: int, msg) -> None:
        """Park a message that arrived before its app registered (the van
        never blocks its receive loop waiting for readiness)."""
        key = (app_id, customer_id)
        with self._customers_cv:
            customer = self._customers.get(key)
            if customer is None:
                queue = self._pending_msgs.setdefault(key, [])
                if not queue:
                    # Loud on first park so a never-registering app shows
                    # up in logs instead of presenting as a silent hang.
                    log.warning(
                        f"parking message for not-yet-registered app {key}"
                    )
                # Overflow is fatal, mirroring the reference's CHECK-fail
                # after its 5 s customer-readiness wait (van.cc:428-438):
                # silently dropping a KV message strands the sender's
                # wait_request forever — fail loud instead.
                log.check(
                    len(queue) < self._MAX_PENDING_PER_APP,
                    f"pending buffer overflow for app {key}: "
                    f"{len(queue)} messages parked but the app never "
                    f"registered a customer — misconfigured app_id or the "
                    f"app failed to start",
                )
                queue.append(msg)
                return
            customer.accept(msg)

    def get_customer(self, app_id: int, customer_id: int, timeout: float = 0.0):
        key = (app_id, customer_id)
        with self._customers_cv:
            if timeout > 0:
                self._customers_cv.wait_for(
                    lambda: key in self._customers, timeout
                )
            return self._customers.get(key)

    def remove_customer(self, customer) -> None:
        with self._customers_cv:
            self._customers.pop((customer.app_id, customer.customer_id), None)

    # -- heartbeats ----------------------------------------------------------

    def update_heartbeat(self, node_id: int, t: float) -> None:
        with self._heartbeat_mu:
            prev = self._heartbeats.get(node_id)
            self._heartbeats[node_id] = t
        if prev is not None and t > prev:
            # Observed beat gap: the failure detector's raw signal —
            # a p99 creeping toward PS_HEARTBEAT_TIMEOUT is the early
            # warning a threshold alone never gives (lo=1ms scale).
            self.metrics.histogram("heartbeat.gap_s", lo=1e-3).observe(
                t - prev
            )

    def get_dead_nodes(self, timeout_s: float = 60) -> List[int]:
        """Nodes silent for > timeout_s (reference: postoffice.cc:285-304).

        Never-heartbeated nodes age from their registration-time seed
        (the scheduler seeds every registrant on ADD_NODE; non-scheduler
        nodes seed the scheduler's entry on roster receipt) rather than
        from process ``_start_time`` — a node that registered late must
        get a full heartbeat window before it can be declared dead.
        ``_start_time`` remains only as the fallback for nodes that were
        somehow never seeded."""
        if timeout_s == 0:
            return []
        dead: List[int] = []
        now = time.time()
        expected = self.get_node_ids(
            WORKER_GROUP + SERVER_GROUP if self.is_scheduler else SCHEDULER_GROUP
        )
        with self._heartbeat_mu:
            for node_id in expected:
                last = self._heartbeats.get(node_id, self._start_time)
                if last + timeout_s < now:
                    dead.append(node_id)
        return dead

    # -- cluster telemetry (METRICS_PULL — docs/observability.md) ------------

    def telemetry_snapshot(self) -> dict:
        """This node's registry snapshot plus identity, the payload a
        METRICS_PULL reply carries (and what psmon renders per node)."""
        # Wire-plane shards flush lazily every few dozen ops; drain them
        # (and the native core's counter block) so the snapshot never
        # reads a stale plane.  Best-effort: a dying transport must not
        # break an unrelated snapshot.
        try:
            self.van.wire_sync()
        except Exception:  # noqa: BLE001
            pass
        snap = {
            "node_id": self.van.my_node.id,
            "role": self.role_str(),
            "rank": (
                id_to_rank(self.van.my_node.id)
                if self.van.my_node.id > 1 else 0
            ),
            "wall_time": time.time(),
            "metrics": self.metrics.snapshot(),
        }
        rt = self.current_routing()
        if rt is not None:
            # Elastic membership context (docs/elasticity.md): psmon's
            # epoch column and per-node owned-range view come from here.
            routing = {
                "epoch": rt.epoch,
                "active": list(rt.active),
                "leaving": list(rt.leaving),
            }
            if self.is_server:
                routing["owned"] = [
                    [r.begin, r.end]
                    for r in rt.ranges_of(self.my_group_rank())
                ]
            snap["routing"] = routing
        ns = getattr(self, "model_namespace", None)
        if ns:
            # Published model version (docs/serving_reads.md): psmon's
            # namespace line in the membership block.
            snap["namespace"] = ns
        return snap

    def absorb_metrics_reply(self, msg: Message) -> None:
        """Van hook: a node's METRICS_PULL response arrived."""
        try:
            snap = json.loads(msg.meta.body.decode())
        except Exception as exc:  # noqa: BLE001 - a corrupt reply must
            log.warning(f"bad METRICS_PULL reply: {exc!r}")  # not wedge
            snap = {"node_id": msg.meta.sender, "error": repr(exc)}
        with self._metrics_cv:
            self._metrics_last_seen[msg.meta.sender] = time.time()
            if msg.meta.timestamp != self._metrics_token:
                return  # stale reply from an earlier (timed-out) pull
            self._metrics_replies[msg.meta.sender] = snap
            self._metrics_cv.notify_all()

    def collect_cluster_metrics(self, timeout_s: float = 5.0) -> Dict[int, dict]:
        """Scheduler-side cluster snapshot: broadcast METRICS_PULL to
        every live worker/server, gather their registry snapshots, and
        include the scheduler's own — ``{node_id: snapshot}``.  Nodes
        that fail to answer within ``timeout_s`` are simply absent
        (psmon flags them); a down peer is skipped up front."""
        log.check(self.is_scheduler,
                  "collect_cluster_metrics runs on the scheduler")
        with self._collect_mu:
            peers = [
                i for i in self.get_node_ids(WORKER_GROUP + SERVER_GROUP)
                if not self.van.is_peer_down(i)
            ]
            with self._metrics_cv:
                self._metrics_token += 1
                token = self._metrics_token
                self._metrics_replies = {}
            reached = 0
            for peer in peers:
                msg = Message()
                msg.meta.recver = peer
                msg.meta.sender = self.van.my_node.id
                msg.meta.request = True
                msg.meta.timestamp = token
                msg.meta.control = Control(cmd=Command.METRICS_PULL)
                try:
                    self.van.send(msg)
                    reached += 1
                except Exception as exc:  # noqa: BLE001 - a dead peer
                    # must not fail the whole pull — and must not count
                    # toward the expected replies either, or every pull
                    # would stall the full timeout waiting on a peer
                    # that was never asked.
                    log.warning(f"METRICS_PULL to {peer} failed: {exc!r}")
            deadline = time.monotonic() + timeout_s
            with self._metrics_cv:
                while len(self._metrics_replies) < reached:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._metrics_cv.wait(remaining)
                replies = dict(self._metrics_replies)
            out = {self.van.my_node.id: self.telemetry_snapshot()}
            out.update(replies)
            return out

    def metrics_last_seen(self) -> Dict[int, float]:
        """Scheduler-side: wall time of each node's most recent
        METRICS_PULL reply — psmon renders nodes missing from the
        newest pull with a last-seen age instead of dropping them."""
        with self._metrics_cv:
            return dict(self._metrics_last_seen)

    # -- tail-trace pull plane (TRACE_PULL — docs/observability.md) ----------

    def trace_collector(self):
        """The scheduler's cross-node trace assembler (lazily built;
        ``telemetry.TraceCollector``)."""
        if self._trace_collector is None:
            from .telemetry.trace_store import TraceCollector

            self._trace_collector = TraceCollector(
                ttl_s=self.env.find_float("PS_TRACE_TTL", 30.0),
                max_traces=self.env.find_int("PS_TRACE_KEEP", 4096),
            )
        return self._trace_collector

    def absorb_trace_reply(self, msg: Message) -> None:
        """Van hook: a node's TRACE_PULL reply arrived."""
        try:
            rep = json.loads(msg.meta.body.decode())
        except Exception as exc:  # noqa: BLE001 - one corrupt reply
            log.warning(f"bad TRACE_PULL reply: {exc!r}")  # can't wedge
            rep = {"node_id": msg.meta.sender, "error": repr(exc)}
        with self._metrics_cv:
            if msg.meta.timestamp != self._trace_token:
                return  # stale reply from an earlier (timed-out) pull
            self._trace_replies[msg.meta.sender] = rep
            self._metrics_cv.notify_all()

    def _tail_hints(self) -> dict:
        """Tail-keep threshold hints piggybacked on the TRACE_PULL
        broadcast: windowed push/pull latency quantiles from the
        ClusterHistory sampler (docs/observability.md).  Empty without
        a history — nodes then fall back to their local histograms."""
        h = self.history
        if h is None or h.samples < 2:
            return {}
        hints: Dict[str, dict] = {}
        for path, hist in (("push", "kv.push_latency_s"),
                           ("pull", "kv.pull_latency_s")):
            for q, label in ((0.9, "p90"), (0.95, "p95"), (0.99, "p99")):
                worst = None
                for nid in h.node_ids():
                    if h.role_of(nid) != "worker":
                        continue
                    v = h.window_quantile(nid, hist, q)
                    if v is not None and (worst is None or v > worst):
                        worst = v
                if worst is not None:
                    hints.setdefault(path, {})[label] = worst
        return hints

    def collect_cluster_traces(self, timeout_s: float = 5.0):
        """Scheduler-side live trace assembly: broadcast TRACE_PULL to
        every live node (piggybacking tail-threshold hints), drain the
        replies' span rings into the :meth:`trace_collector`, retire
        expired partials, and return the collector.  Shares the
        METRICS_PULL collect lock so concurrent pulls serialize."""
        log.check(self.is_scheduler,
                  "collect_cluster_traces runs on the scheduler")
        hints = self._tail_hints()
        body = json.dumps({"hints": hints}).encode() if hints else b""
        with self._collect_mu:
            peers = [
                i for i in self.get_node_ids(WORKER_GROUP + SERVER_GROUP)
                if not self.van.is_peer_down(i)
            ]
            with self._metrics_cv:
                self._trace_token += 1
                token = self._trace_token
                self._trace_replies = {}
            reached = 0
            for peer in peers:
                msg = Message()
                msg.meta.recver = peer
                msg.meta.sender = self.van.my_node.id
                msg.meta.request = True
                msg.meta.timestamp = token
                msg.meta.body = body
                msg.meta.control = Control(cmd=Command.TRACE_PULL)
                try:
                    self.van.send(msg)
                    reached += 1
                except Exception as exc:  # noqa: BLE001 - a dead peer
                    # must neither fail the pull nor stall the gather.
                    log.warning(f"TRACE_PULL to {peer} failed: {exc!r}")
            deadline = time.monotonic() + timeout_s
            with self._metrics_cv:
                while len(self._trace_replies) < reached:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._metrics_cv.wait(remaining)
                replies = dict(self._trace_replies)
        coll = self.trace_collector()
        # The scheduler's own ring drains locally (it rarely records,
        # but a complete pull must not special-case the puller).
        spans, evicted = self.tracer.drain()
        coll.ingest(self.van.my_node.id, self.role_str(), spans,
                    [e for e in self.flight.events() if e.get("trace")],
                    evicted=evicted)
        for nid, rep in replies.items():
            coll.ingest(nid, rep.get("role", "?"),
                        rep.get("spans") or [], rep.get("flight") or [],
                        evicted=rep.get("evicted") or 0)
        coll.retire()
        return coll

    # -- coordinated snapshots (docs/durability.md) --------------------------

    def register_snapshot_hook(self, hook: Callable[[Message], bool]) -> None:
        """``hook(msg)`` receives SNAPSHOT control requests on the van
        pump and returns True when it took ownership of the reply
        (KVServer posts the fence through its request queue and answers
        from there).  Keep hooks fast — never block on the van."""
        with self._snapshot_mu:
            self._snapshot_hooks.append(hook)

    def unregister_snapshot_hook(self, hook) -> None:
        with self._snapshot_mu:
            try:
                self._snapshot_hooks.remove(hook)
            except ValueError:
                pass

    def notify_snapshot(self, msg: Message) -> bool:
        """Run the snapshot hooks; True when one consumed the request."""
        with self._snapshot_mu:
            hooks = list(self._snapshot_hooks)
        for hook in hooks:
            try:
                if hook(msg):
                    return True
            except Exception as exc:  # noqa: BLE001 - isolate hooks
                log.warning(f"snapshot hook failed: {exc!r}")
        return False

    def absorb_snapshot_reply(self, msg: Message) -> None:
        """Van hook: a server's SNAPSHOT reply arrived (scheduler)."""
        try:
            rep = json.loads(msg.meta.body.decode())
        except Exception as exc:  # noqa: BLE001 - one corrupt reply
            rep = {"error": f"bad reply: {exc!r}"}
        with self._metrics_cv:
            if msg.meta.timestamp != self._snapshot_token:
                return  # stale reply from an earlier (timed-out) cut
            self._snapshot_replies[msg.meta.sender] = rep
            self._metrics_cv.notify_all()

    def snapshot(self, directory: Optional[str] = None,
                 timeout_s: float = 60.0,
                 settle_timeout_s: float = 10.0) -> dict:
        """Coordinate one consistent-cut cluster snapshot
        (docs/durability.md): broadcast ``Command.SNAPSHOT`` to every
        live server, gather their per-range digests, and COMMIT the cut
        by writing the cluster manifest.  Scheduler only.  Raises when
        any server errored or failed to answer — a partial snapshot is
        never committed (the stale manifest, if any, stays the restore
        point).

        A cut is DEFERRED while any range migration is in flight
        (``settle_timeout_s`` bounds the wait, then the cut is vetoed
        loudly): a SNAPSHOT broadcast landing mid-handoff would cut a
        range whose state is split across the old and new owner."""
        log.check(self.is_scheduler, "snapshot runs on the scheduler")
        directory = directory or self.snapshot_dir
        log.check(bool(directory),
                  "snapshot needs a directory (PS_SNAPSHOT_DIR or the "
                  "directory= argument)")
        settle_by = time.monotonic() + settle_timeout_s
        deferred = False
        while True:
            pending = self.migrations_in_flight()
            if not pending:
                break
            if not deferred:
                deferred = True
                self.flight.record(
                    "snapshot_deferred", severity="warn",
                    pending=[list(p) for p in pending[:4]],
                    count=len(pending),
                )
            if time.monotonic() >= settle_by:
                log.check(False, f"snapshot vetoed: {len(pending)} range "
                                 f"migration(s) still in flight after "
                                 f"{settle_timeout_s:g}s (epochs "
                                 f"{sorted({e for e, _ in pending})})")
            time.sleep(0.05)
        from .kv import snapshot as snap_mod

        t0 = time.monotonic()
        rt = self.current_routing()
        epoch = rt.epoch if rt is not None else -1
        self.flight.record("snapshot_begin", severity="info",
                           dir=directory, epoch=epoch)
        # Per-attempt uid: servers stamp it into their segment
        # filenames so a vetoed attempt can never overwrite the
        # previously committed snapshot's bytes (snapshot.py).
        uid = f"{os.getpid():x}-{int(time.time() * 1000):x}"
        body = json.dumps({"dir": directory, "epoch": epoch,
                           "uid": uid}).encode()
        peers = [
            i for i in self.get_node_ids(SERVER_GROUP)
            if not self.van.is_peer_down(i)
        ]
        log.check(bool(peers), "snapshot: no live servers")
        with self._metrics_cv:
            self._snapshot_token += 1
            token = self._snapshot_token
            self._snapshot_replies = {}
        reached = []
        for peer in peers:
            msg = Message()
            msg.meta.recver = peer
            msg.meta.sender = self.van.my_node.id
            msg.meta.request = True
            msg.meta.timestamp = token
            msg.meta.body = body
            msg.meta.control = Control(cmd=Command.SNAPSHOT)
            try:
                self.van.send(msg)
                reached.append(peer)
            except Exception as exc:  # noqa: BLE001 - dead peer vetoes
                log.warning(f"SNAPSHOT to {peer} failed: {exc!r}")
        deadline = time.monotonic() + timeout_s
        with self._metrics_cv:
            while len(self._snapshot_replies) < len(reached):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._metrics_cv.wait(remaining)
            replies = dict(self._snapshot_replies)
        entries, errors = snap_mod.snapshot_summary(replies)
        silent = [p for p in peers if p not in replies]
        if silent:
            errors.append(f"no reply from node(s) {silent} within "
                          f"{timeout_s}s")
        if errors:
            self.flight.record("snapshot_end", severity="warn",
                               ok=False, errors=errors[:4])
            log.check(False, "snapshot NOT committed: "
                             + "; ".join(errors))
        manifest = snap_mod.write_manifest(
            directory, epoch, entries,
            extra={"servers": len(replies), "uid": uid},
        )
        # The new manifest is durable: the previous snapshot's (and
        # any vetoed attempt's) segment files are garbage now.
        snap_mod.prune_segments(
            directory, {"ranges": entries},
        )
        dur = time.monotonic() - t0
        self.metrics.histogram("snapshot.duration_s").observe(dur)
        self.flight.record(
            "snapshot_end", severity="info", ok=True,
            keys=sum(e["keys"] for e in entries),
            bytes=sum(e["nbytes"] for e in entries),
            duration_s=round(dur, 3),
        )
        return {
            "manifest": manifest,
            "epoch": epoch,
            "ranges": entries,
            "servers": len(replies),
            "duration_s": dur,
        }

    def retune_apply(self, task_bytes: int,
                     timeout_s: float = 30.0) -> dict:
        """Live-retune the apply task quantum on every server
        (docs/apply_shards.md): one ``retune`` control op on the
        SNAPSHOT channel, so it serializes behind every earlier queued
        request exactly like a namespace flip.  The autopilot's
        apply_wait actuator; also a manual operator lever."""
        task_bytes = int(task_bytes)
        log.check(task_bytes > 0, "retune_apply needs task_bytes > 0")
        replies = self._model_ctl(
            {"op": "retune", "apply_task_bytes": task_bytes}, timeout_s)
        applied = sum(1 for r in replies.values()
                      if r.get("applied", {}).get("apply_task_bytes"))
        self.flight.record("apply_retune", severity="info",
                           task_bytes=task_bytes, servers=len(replies),
                           applied=applied)
        return {"task_bytes": task_bytes, "servers": len(replies),
                "applied": applied}

    def snapshot_status(self) -> dict:
        """Age and summary of the newest committed manifest (any
        role; psmon's snapshot-age line reads the server gauges, this
        is the library view)."""
        from .kv import snapshot as snap_mod

        manifest = snap_mod.load_manifest(self.snapshot_dir)
        return {
            "dir": self.snapshot_dir,
            "age_s": snap_mod.manifest_age_s(self.snapshot_dir),
            "epoch": manifest.get("epoch") if manifest else None,
            "ranges": len(manifest.get("ranges", [])) if manifest
            else 0,
        }

    # -- model namespaces (docs/serving_reads.md) ----------------------------

    def _model_ctl(self, body: dict, timeout_s: float) -> Dict[int, dict]:
        """Broadcast one namespace control op to every live server on
        the SNAPSHOT channel and gather their replies; raises when any
        server errors or stays silent — an op half-applied across the
        fleet must fail loudly, never serve mixed versions silently."""
        log.check(self.is_scheduler, "namespace ops run on the scheduler")
        payload = json.dumps(body).encode()
        peers = [
            i for i in self.get_node_ids(SERVER_GROUP)
            if not self.van.is_peer_down(i)
        ]
        log.check(bool(peers), "namespace op: no live servers")
        with self._metrics_cv:
            self._snapshot_token += 1
            token = self._snapshot_token
            self._snapshot_replies = {}
        reached = []
        for peer in peers:
            msg = Message()
            msg.meta.recver = peer
            msg.meta.sender = self.van.my_node.id
            msg.meta.request = True
            msg.meta.timestamp = token
            msg.meta.body = payload
            msg.meta.control = Control(cmd=Command.SNAPSHOT)
            try:
                self.van.send(msg)
                reached.append(peer)
            except Exception as exc:  # noqa: BLE001 - dead peer vetoes
                log.warning(f"namespace op to {peer} failed: {exc!r}")
        deadline = time.monotonic() + timeout_s
        with self._metrics_cv:
            while len(self._snapshot_replies) < len(reached):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._metrics_cv.wait(remaining)
            replies = dict(self._snapshot_replies)
        errors = [f"node {n}: {r['error']}" for n, r in replies.items()
                  if r.get("error")]
        silent = [p for p in peers if p not in replies]
        if silent:
            errors.append(f"no reply from node(s) {silent} within "
                          f"{timeout_s}s")
        log.check(not errors, f"namespace op {body.get('op')!r} failed: "
                              + "; ".join(errors))
        return replies

    def publish_model(self, directory: Optional[str] = None,
                      namespace: str = "model", version: str = "",
                      timeout_s: float = 60.0) -> dict:
        """Publish a committed snapshot manifest as a model version
        (docs/serving_reads.md): every live server STAGES the manifest
        into an off-line store while serving continues, then — only
        once every stage succeeded — atomically FLIPS to it.  The
        displaced store stays resident for :meth:`rollback_model`."""
        directory = directory or self.snapshot_dir
        log.check(bool(directory),
                  "publish_model needs a snapshot directory "
                  "(PS_SNAPSHOT_DIR or the directory= argument)")
        if not version:
            from .kv import snapshot as snap_mod

            manifest = snap_mod.load_manifest(directory)
            log.check(manifest is not None,
                      f"no committed manifest in {directory!r}")
            version = str(manifest.get("uid")
                          or manifest.get("epoch", 0))
        staged = self._model_ctl(
            {"op": "publish", "dir": directory, "namespace": namespace,
             "version": version}, timeout_s)
        flipped = self._model_ctl(
            {"op": "flip", "namespace": namespace, "version": version},
            timeout_s)
        self.flight.record("model_published", severity="info",
                           namespace=namespace, version=version,
                           servers=len(flipped))
        return {
            "namespace": namespace,
            "version": version,
            "servers": len(flipped),
            "keys": sum(int(r.get("keys", 0)) for r in staged.values()),
        }

    def rollback_model(self, timeout_s: float = 60.0) -> dict:
        """Instant rollback: every live server swaps the displaced
        store back in — one pointer swap per server, no disk reads."""
        replies = self._model_ctl({"op": "rollback"}, timeout_s)
        first = next(iter(replies.values()), {})
        self.flight.record("model_rollback", severity="info",
                           namespace=first.get("namespace"),
                           version=first.get("version"))
        return {
            "namespace": first.get("namespace"),
            "version": first.get("version"),
            "servers": len(replies),
        }

    # -- continuous telemetry plane (docs/observability.md) ------------------

    def start_history(self, interval_s: Optional[float] = None):
        """Build (and start, when the interval is positive) the
        scheduler's :class:`~.telemetry.ClusterHistory` sampler +
        watchdog.  Idempotent; returns the history."""
        log.check(self.is_scheduler, "ClusterHistory runs on the scheduler")
        if self.history is None:
            from .telemetry.timeseries import ClusterHistory

            self.history = ClusterHistory(
                po=self, env=self.env, interval_s=interval_s
            )
            # Autopilot (docs/autopilot.md): constructed ONLY when
            # PS_AUTOPILOT opts in — unset leaves the ingest path (and
            # the wire) bit-identical to a build without the engine.
            from .cluster.autopilot import parse_mode

            mode = parse_mode(self.env.find("PS_AUTOPILOT"))
            if mode is not None:
                from .cluster.autopilot import Autopilot

                self.history.autopilot = Autopilot(
                    self, env=self.env, mode=mode)
        if interval_s is not None and interval_s > 0:
            self.history.interval_s = float(interval_s)
        if self.history.interval_s > 0 and not self.history.running:
            self.history.start()
        return self.history

    def stop_history(self) -> None:
        h = self.history
        if h is not None:
            h.stop()

    def health(self, min_severity: str = "warn",
               since: Optional[float] = None) -> List:
        """The watchdog's :class:`~.telemetry.HealthEvent` findings
        (scheduler-side; empty on nodes without a history — per-node
        fault context lives in ``po.flight`` instead)."""
        h = self.history
        if h is None:
            return []
        return h.watchdog.events(min_severity=min_severity, since=since)

    # -- node failure hooks --------------------------------------------------

    def register_node_failure_hook(
        self, hook: Callable[[int, bool], None]
    ) -> None:
        """Register ``hook(node_id, down)``: called with ``down=True``
        when the failure detector declares ``node_id`` dead, and
        ``down=False`` when a recovered replacement rejoins under that
        id.  Hooks run on van/detector threads — keep them fast and
        never let them block on the van."""
        with self._node_failure_mu:
            self._node_failure_hooks.append(hook)

    def unregister_node_failure_hook(
        self, hook: Callable[[int, bool], None]
    ) -> None:
        with self._node_failure_mu:
            try:
                self._node_failure_hooks.remove(hook)
            except ValueError:
                pass

    def notify_node_failure(self, node_id: int, down: bool = True) -> None:
        """Run the failure hooks (exceptions logged, never propagated —
        one bad hook must not stop the others or kill the van pump)."""
        with self._node_failure_mu:
            hooks = list(self._node_failure_hooks)
        for hook in hooks:
            try:
                hook(node_id, down)
            except Exception as exc:  # noqa: BLE001 - isolate hooks
                log.warning(f"node failure hook failed: {exc!r}")
