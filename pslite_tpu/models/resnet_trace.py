"""ResNet-50 gradient push/pull trace (BASELINE config 4).

BytePS's flagship workload is the ResNet-50 gradient stream: ~25.5M fp32
params (~102 MB) pushed and pulled every step.  The reference has no model
code; the trace is the traffic shape.  We synthesize the exact per-tensor
sizes from the architecture ([3,4,6,3] bottleneck blocks) and replay them
through the collective engine as bucketed dense push_pulls.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def resnet50_param_sizes() -> List[Tuple[str, int]]:
    """(name, float32 element count) per tensor, ~25.5M total."""
    sizes: List[Tuple[str, int]] = []

    def conv(name, kh, kw, cin, cout):
        sizes.append((f"{name}.weight", kh * kw * cin * cout))
        sizes.append((f"{name}.bn", 2 * cout))  # gamma+beta

    conv("stem", 7, 7, 3, 64)
    cin = 64
    widths = [(64, 256), (128, 512), (256, 1024), (512, 2048)]
    blocks = [3, 4, 6, 3]
    for stage, ((mid, out), n) in enumerate(zip(widths, blocks)):
        for b in range(n):
            base = f"layer{stage + 1}.{b}"
            conv(f"{base}.conv1", 1, 1, cin, mid)
            conv(f"{base}.conv2", 3, 3, mid, mid)
            conv(f"{base}.conv3", 1, 1, mid, out)
            if b == 0:
                conv(f"{base}.downsample", 1, 1, cin, out)
            cin = out
    sizes.append(("fc.weight", 2048 * 1000))
    sizes.append(("fc.bias", 1000))
    return sizes


def total_params() -> int:
    return sum(n for _, n in resnet50_param_sizes())


def make_buckets(bucket_bytes: int = 4 << 20) -> List[Tuple[str, int]]:
    """Size-bucketing of the gradient stream: small tensors fuse into
    ~partition-sized buckets and oversized tensors split into
    partition-sized chunks (the reference's BYTEPS_PARTITION_BYTES
    semantics, rdma_transport.h:591-617)."""
    buckets: List[Tuple[str, int]] = []
    cur = 0
    idx = 0
    limit = bucket_bytes // 4  # fp32 elements

    def flush():
        nonlocal cur, idx
        if cur:
            buckets.append((f"rn50_bucket{idx}", cur))
            idx += 1
            cur = 0

    for _, n in resnet50_param_sizes():
        while n >= limit:
            flush()
            buckets.append((f"rn50_bucket{idx}", limit))
            idx += 1
            n -= limit
        if cur + n > limit:
            flush()
        cur += n
    flush()
    return buckets


def replay(engine, steps: int = 1, bucket_bytes: int = 4 << 20,
           grouped: bool = True, host_origin: bool = False,
           overlap: bool = True, measure=None):
    """Run the ResNet-50 push/pull trace through a CollectiveEngine.

    ``grouped=True`` pushes the whole gradient stream as ONE jitted
    program per step (engine.push_pull_group) — one dispatch instead of
    ~35; ``False`` replays bucket-by-bucket (the per-message analog).

    ``host_origin=True`` replays the path real users hit: each bucket's
    gradient starts as a host numpy array every step (the framework
    hands the PS CPU tensors).  With ``overlap=True`` the next bucket's
    host->HBM staging runs on a background thread while the current
    bucket's collective executes — the pinned-memory/async-RDMA overlap
    of the reference's host path; ``overlap=False`` stages serially
    (the baseline the overlap is measured against).

    Returns (bytes_moved_per_step, seconds_per_step).
    """
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    buckets = make_buckets(bucket_bytes)
    grads = {}
    host = {}
    sharding = NamedSharding(engine.mesh, P(engine.axis, None))
    for name, n in buckets:
        engine.register_dense(name, np.arange(1, dtype=np.uint64), n)
        bucket = engine.bucket(name)
        if host_origin:
            host[name] = np.ones(
                (engine.num_shards, bucket.padded_len), np.float32
            )
        else:
            g = jnp.ones(
                (engine.num_shards, bucket.padded_len), jnp.float32
            )
            grads[name] = jax.device_put(g, sharding)
    names = [name for name, _ in buckets]
    # Grouped dispatch supports stateless handles only; engines built
    # with fused optimizer handles fall back to per-bucket replay.
    grouped = grouped and not engine.handle_is_stateful and not host_origin

    def one_step():
        if grouped:
            engine.push_pull_group(names, [grads[n] for n in names])
        elif not host_origin:
            for n in names:
                engine.push_pull(n, grads[n])
        elif not overlap:
            for n in names:
                engine.push_pull(n, host[n])
        else:
            # Double-buffered host staging via the engine's hardened
            # stream pipeline: bucket i+1's transfer runs on the stager
            # thread while bucket i's collective dispatches.
            for _ in engine.push_pull_multi_stream(
                ((n, host[n]) for n in names), depth=2
            ):
                pass

    # Warm the executable cache (the rendezvous-equivalent first touch).
    one_step()
    engine.block()

    def loop():
        for _ in range(steps):
            one_step()
        engine.block()

    # ``measure(loop) -> seconds | None`` swaps the clock (e.g. XPlane
    # device-busy seconds instead of host wall time — the only basis the
    # bench trusts under the tunnel); None means the basis is
    # unavailable and propagates to the caller.
    from ..utils.profiling import clocked

    elapsed = clocked(loop, measure)
    dt = elapsed / max(steps, 1) if elapsed is not None else None
    step_bytes = 2 * 4 * sum(n for _, n in buckets)  # push + pull
    return step_bytes, dt
