"""Sharded forward (sp ring attention + Megatron-style TP, and EP MoE)
must match the single-device forward bit-for-tolerance."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pslite_tpu.models.transformer import (
    ModelConfig,
    ParallelCtx,
    forward,
    init_params,
)
from pslite_tpu.parallel.mesh import default_mesh, shard_map_compat
from pslite_tpu.parallel.ring_attention import ring_attention


def _sharded_forward(params, tokens, cfg, mesh, axis="sp", moe=False):
    def local(p, tok_l):
        sp_idx = lax.axis_index(axis)
        ctx = ParallelCtx(
            attn_fn=lambda q, k, v: ring_attention(q, k, v, axis, causal=True),
            pos_offset=sp_idx * tok_l.shape[1],
            tp_axis=None if moe else axis,
            ep_axis=axis if moe else None,
        )
        return forward(p, tok_l, cfg, ctx=ctx)

    fn = shard_map_compat(
        local, mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis, None),
    )
    return jax.jit(fn)(params, tokens)


def test_tp_sp_forward_matches_single_device():
    cfg = ModelConfig(vocab=32, dim=32, heads=2, layers=2)
    mesh = default_mesh(axis_name="sp")
    S = mesh.shape["sp"]
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, size=(2, 4 * S)),
        dtype=jnp.int32,
    )
    ref = forward(params, tokens, cfg)
    out = _sharded_forward(params, tokens, cfg, mesh)
    # bf16 matmuls reduce in different orders across shardings; exactness
    # is checked in float64 (diff == 0.0), tolerance here covers bf16 noise.
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-2, atol=2e-2)


def test_tp_sp_forward_exact_in_float64():
    cfg = ModelConfig(vocab=32, dim=32, heads=2, layers=2, dtype="float64")
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)
    try:
        mesh = default_mesh(axis_name="sp")
        S = mesh.shape["sp"]
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 32, size=(2, 4 * S)),
            dtype=jnp.int32,
        )
        ref = forward(params, tokens, cfg)
        out = _sharded_forward(params, tokens, cfg, mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-9, atol=1e-9)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_ep_moe_forward_matches_single_device():
    cfg = ModelConfig(vocab=32, dim=32, heads=2, layers=1, moe_experts=16)
    mesh = default_mesh(axis_name="sp")
    S = mesh.shape["sp"]
    params = init_params(jax.random.PRNGKey(1), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 32, size=(2, 4 * S)),
        dtype=jnp.int32,
    )
    ref = forward(params, tokens, cfg)
    out = _sharded_forward(params, tokens, cfg, mesh, moe=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-2, atol=2e-2)


def test_moe_gate_receives_gradient():
    """The router must be trainable: d(loss)/d(gate) != 0 (the selected
    expert's output is scaled by its gate probability)."""
    cfg = ModelConfig(vocab=16, dim=16, heads=2, layers=1, moe_experts=4)
    params = init_params(jax.random.PRNGKey(3), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 16, size=(2, 8)), jnp.int32
    )

    def loss(p):
        return forward(p, tokens, cfg).sum()

    grads = jax.grad(loss)(params)
    gate_grad = np.asarray(grads["layers"][0]["moe"]["gate"])
    assert np.abs(gate_grad).max() > 0


def test_moe_single_device_routes_all_tokens():
    cfg = ModelConfig(vocab=16, dim=16, heads=2, layers=1, moe_experts=4)
    params = init_params(jax.random.PRNGKey(2), cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    logits = forward(params, tokens, cfg)
    assert np.isfinite(np.asarray(logits)).all()
