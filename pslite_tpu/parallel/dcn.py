"""Cross-slice (DCN) tier: hierarchical dense push/pull.

A TPU pod slice talks ICI internally; slices talk to each other over DCN.
The reference's analogous structures are BytePS's hierarchical reduction
and the MultiVan rail composition (multi_van.h:173-197: route each
message across N inner vans).  Here the two tiers compose the two
existing data planes:

1. **ICI tier** — intra-slice aggregation: one fused
   ``psum_scatter + all_gather`` (an all-reduce) on the slice's
   :class:`CollectiveEngine`, producing the slice-local gradient sum.
2. **DCN tier** — inter-slice exchange: each slice's leader pushes the
   slice-sum through the ordinary KV message path (:class:`KVWorker`
   over a socket van).  The default slicer shards the keys across the
   global servers, so DCN traffic is key-range partitioned across
   server rails exactly like MultiVan routes across its inner vans; the
   server handler applies the update (sum / optimizer — the same
   pluggable handle contract, kv_app.h:430-452).
3. **Redistribute** — the pulled global aggregate is placed replicated
   onto the slice mesh for consumption by the slice's devices.

The leader barriers on the worker group between push and pull so every
slice's contribution lands before any slice reads the aggregate (the
synchronous-SGD pattern of the reference's docs/overview.md).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..base import WORKER_GROUP
from ..utils import logging as log


class DcnKVWorker:
    """Hierarchical dense push/pull: slice mesh (ICI) + KV messages (DCN).

    ``kv_worker`` is this slice leader's :class:`KVWorker` on a socket
    van connecting the slices; ``slice_engine`` is the slice's
    :class:`CollectiveEngine`.  One instance per slice leader process.
    """

    def __init__(self, kv_worker, slice_engine, barrier=True,
                 compress: Optional[str] = None):
        """``compress='int8'`` quantizes both DCN directions (push and
        pull) blockwise — 4x fewer bytes on the slow inter-slice link,
        where the reference's analogous lever is BytePS gradient
        compression; the ICI tier stays full precision."""
        self.kv = kv_worker
        self.engine = slice_engine
        self._barrier = barrier
        self._compress = compress
        self._keys: dict = {}

    def register_dense(self, name: str, keys, val_len: int,
                       dtype=None) -> None:
        """Register the bucket on both tiers (engine scratch + KV keys)."""
        keys = np.ascontiguousarray(np.asarray(keys, dtype=np.uint64))
        self.engine.register_dense(name, keys, val_len, dtype=dtype)
        self._keys[name] = keys

    def push_pull(self, name: str, grads, out: Optional[np.ndarray] = None):
        """grads: this slice's worker rows ([W_slice, total] or [total]).

        Returns the global (all-slice) aggregate as a host array, also
        written to ``out`` when given.  Synchronous across slices.
        """
        (out,) = self.push_pull_group([name], [grads], outs=[out])
        return out

    def push_pull_group(self, names, grads_list, outs=None):
        """Overlapped multi-bucket round: dispatch every slice-sum on the
        ICI tier (async), push each over DCN as its device result lands,
        ONE barrier, pull all, wait all, one closing barrier.

        vs. per-bucket push_pull this pipelines socket IO with device
        compute and amortizes the two sync barriers across the whole
        round — the multi-bucket analog of the reference's one-Message-
        per-server slicing (kv_app.h:638-683), where one timestamp
        covers many keys."""
        log.check(len(names) == len(grads_list),
                  "names/grads length mismatch")
        log.check(len(set(names)) == len(names),
                  "duplicate bucket in group")
        for name in names:
            log.check(name in self._keys, f"bucket {name!r} not registered")
        if outs is None:
            outs = [None] * len(names)
        log.check(len(outs) == len(names), "names/outs length mismatch")
        # ICI tier: slice-local all-reduce per bucket.  handle="assign"
        # makes the engine store pure scratch (store := slice sum), so
        # the global accumulation semantics live only at the DCN servers.
        # Dispatch is async — all buckets' collectives enqueue before the
        # first DCN push blocks on device completion.
        device_sums = [
            self.engine.push_pull(name, grads, handle="assign")
            for name, grads in zip(names, grads_list)
        ]
        # DCN tier: key-range-sharded pushes to the global servers (each
        # np.asarray blocks only on ITS bucket; later buckets still
        # compute while earlier bytes are on the wire), then one barrier
        # so every slice's pushes are applied before any pull.
        cust = self.kv._customer.customer_id
        push_ts = [
            self.kv.push(self._keys[name], np.asarray(dev),
                         compress=self._compress)
            for name, dev in zip(names, device_sums)
        ]
        for ts in push_ts:
            self.kv.wait(ts)
        if self._barrier:
            self.kv.po.barrier(cust, WORKER_GROUP)
        results = []
        pull_ts = []
        for name, out in zip(names, outs):
            bucket = self.engine.bucket(name)
            if out is None:
                out = np.empty(bucket.total_len,
                               dtype=np.dtype(bucket.dtype))
            results.append(out)
            pull_ts.append(
                self.kv.pull(self._keys[name], out,
                             compress=self._compress)
            )
        for ts in pull_ts:
            self.kv.wait(ts)
        if self._barrier:
            # Post-pull barrier: without it a fast slice's NEXT-round push
            # could land at the sum-accumulating servers before a slow
            # slice finishes reading THIS round's aggregate.
            self.kv.po.barrier(cust, WORKER_GROUP)
        return results

    def to_device(self, name: str, host_aggregate):
        """Place the pulled aggregate replicated onto the slice mesh (the
        intra-slice redistribution step)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(self.engine.mesh, P(None))
        return jax.device_put(np.asarray(host_aggregate), sharding)
