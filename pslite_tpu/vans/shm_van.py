"""ShmVan — same-host IPC fast path: meta over TCP, data via /dev/shm.

Equivalent of the reference's IPCTransport inside the RDMA van
(rdma_transport.h:469-633, ``BYTEPS_ENABLE_IPC=1``): when peers share a
host, payloads move through named shared-memory segments (one per
(sender, recver, key, direction) — the ``BytePS_ShM_<key>`` pattern) and
only the small meta message crosses the socket.  The receiver maps the
segment and aliases it zero-copy into the delivered SArray.

As in the reference, a segment is reused across iterations of the same key,
which assumes at most one outstanding message per (key, direction) — the
same contract the reference's registered buffers impose
(kv_app.h:210-217).
"""

from __future__ import annotations

import base64
import copy
import ctypes
import json
import mmap
import os
import threading
from typing import Dict, Optional

import numpy as np

from .. import wire
from ..message import Message, OPT_COMPRESS_INT8, OPT_ZPULL, ZPULL_OFF_BITS
from ..sarray import SArray
from ..utils import logging as log
from .tcp_van import TcpVan

_SHM_DIR = "/dev/shm"

# Payloads at least this large go through the native parallel-copy pool
# (chunks below it aren't worth the handoff).
_COPY_POOL_MIN = 1 << 20


class _Segment:
    def __init__(self, name: str, size: int, create: bool):
        self.name = name
        self.path = os.path.join(_SHM_DIR, name)
        self.created = create
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(self.path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, size)
            else:
                size = os.fstat(fd).st_size
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.size = size

    def close(self, unlink: bool = False) -> None:
        try:
            self.mm.close()
        except BufferError:
            pass  # numpy views still alive; the mapping dies with them
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class ShmVan(TcpVan):
    """TCP control/meta plane + shared-memory data plane for same-host
    peers; remote peers transparently use plain TCP frames.

    Safe under the Van's per-peer send lanes: ``send_msg`` runs under
    the owning peer's transmit lock, segment names embed (sender,
    recver, key, direction), and ``_seg_mu`` guards only the segment
    map — so lanes to different peers copy into disjoint segments
    concurrently (the copy pool parallelizes WITHIN a copy as well)."""

    def __init__(self, postoffice):
        super().__init__(postoffice)
        self._segments: Dict[str, _Segment] = {}
        self._seg_mu = threading.Lock()
        self._ns = self.env.find("PS_SHM_NS", str(os.getpid()))
        self._peer_hosts: Dict[int, str] = {}
        self._min_bytes = self.env.find_int("PS_SHM_MIN_BYTES", 4096)
        self._pull_ns_cache: Optional[str] = None
        # Registered push recv buffers (_push_recv_bufs) are inherited
        # from TcpVan; this van's deliver hook reuses the base logic with
        # _copy_into routed through the native parallel-copy pool.
        # Native parallel-copy pool for multi-MB segment writes — the
        # reference IPC transport's copy-thread-pool
        # (BYTEPS_IPC_COPY_NUM_THREADS=4, rdma_transport.h:570-589).
        # Process-wide and process-lived: co-located vans share it, and a
        # van shutting down can never free it under a peer's in-flight
        # copy.  Gated on library availability AND this node's
        # _native_allowed (the PER-NODE Environment's PS_NATIVE —
        # load()'s os.environ check cannot see the override maps
        # in-process multi-node tests use, so a node-level PS_NATIVE=0
        # must be honored here), not on TcpVan's core-count auto-select:
        # the pool only engages on multi-MB copies and has no
        # per-message handoff cost, so it is harmless on single-core
        # (PARITY 3b).
        self._copy_pool = None
        n_copy = self.env.find_int("PS_SHM_COPY_THREADS", 4)
        if n_copy > 0 and self._native_allowed:
            from . import native as _native_mod

            if _native_mod.load(self.env) is not None:
                self._copy_pool = _native_mod.shared_copy_pool(
                    n_copy, self.env
                )
        # PS_SHM_RING=1: same-host peers exchange their WHOLE meta stream
        # through shared-memory SPSC byte pipes instead of TCP — the
        # reference's in-process lock-free SPSC queue (spsc_queue.h,
        # DMLC_LOCKLESS_QUEUE) extended across processes.  Payload bytes
        # still ride the /dev/shm segments; the pipe replaces the socket,
        # so per-pair ordering is exactly stream ordering.
        #
        # Asymmetric config (sender rings, receiver doesn't — env
        # mismatch or watch failure) is survivable: the native writer
        # probes the reader-liveness heartbeat in the pipe header on
        # ring-full waits and after PS_SHM_RING_DEAD_MS (default 5000)
        # of silence retires the pipe and reroutes this peer's stream
        # to the socket, logging to stderr (tests/test_pipe_fallback.py).
        self._pipe_mode = False
        self._pipe_bytes = self.env.find_int("PS_SHM_RING_BYTES", 1 << 22)
        if self.env.find_int("PS_SHM_RING", 0):
            if self._native is None and self._native_allowed:
                # Ring pipes ARE the native meta plane — asking for them
                # is an explicit opt-in that overrides the core-count
                # auto-select (which only judges the TCP offload's
                # per-message handoffs).  It does NOT override this
                # node's PS_NATIVE=0: the documented contract is that
                # PS_NATIVE=0 forces the pure-Python path, per node.
                from . import native as _native_mod

                if _native_mod.load(self.env) is not None:
                    self._native = _native_mod.NativeTransport()
            if self._native is not None:
                self._pipe_mode = True
            else:
                log.warning(
                    "PS_SHM_RING needs the native core (make -C cpp, "
                    "and PS_NATIVE not 0); staying on sockets"
                )

    def bind_transport(self, node, max_retry: int) -> int:
        port = super().bind_transport(node, max_retry)
        if self._pipe_mode:
            # Watch for inbound pipes targeting my port.  Glob discovery
            # (no announce handshake): a booting peer sends ADD_NODE
            # before anyone knows its identity, so the receiver must find
            # the pipe by name alone.
            self._native.pipe_watch(
                _SHM_DIR, f"pslpipe_{self._pull_ns}_", f"_{port}",
                self.env.find_int("PS_SHM_RING_IDLE_US", 0),
            )
        return port

    def connect_transport(self, node, deadline: float = 60.0,
                          timeout_s: float = 30.0) -> None:
        super().connect_transport(node, deadline, timeout_s)
        if node.id >= 0:
            self._peer_hosts[node.id] = node.hostname
            if (
                self._pipe_mode
                and node.port
                and self.my_node.port
                and node.hostname == self.my_node.hostname
            ):
                path = os.path.join(
                    _SHM_DIR,
                    f"pslpipe_{self._pull_ns}"
                    f"_{self.my_node.port}_{node.port}",
                )
                try:
                    self._native.pipe_connect(
                        node.id, path, self._pipe_bytes
                    )
                except OSError as exc:
                    log.warning(
                        f"shm pipe to node {node.id} unavailable "
                        f"({exc!r}); staying on the socket"
                    )

    def _same_host(self, recver: int) -> bool:
        host = self._peer_hosts.get(recver)
        return host is not None and host == self.my_node.hostname

    def _segment(self, name: str, size: int, create: bool) -> _Segment:
        with self._seg_mu:
            seg = self._segments.get(name)
            if seg is not None and seg.size >= size:
                return seg
            if seg is not None:
                seg.close(unlink=seg.created)
                # Drop the entry NOW: if re-creation below raises (e.g.
                # /dev/shm exhausted), a cached closed segment would
                # poison every later send for this key.
                del self._segments[name]
            seg = _Segment(name, size, create)
            self._segments[name] = seg
            return seg

    def _copy_into(self, dst_addr: int, arr: np.ndarray) -> None:
        """One copy path for every payload: multi-MB copies spread across
        the shared native pool's threads, the rest memmove inline."""
        if self._copy_pool is not None and arr.nbytes >= _COPY_POOL_MIN:
            self._copy_pool.copy(dst_addr, arr.ctypes.data, arr.nbytes)
        else:
            ctypes.memmove(dst_addr, arr.ctypes.data, arr.nbytes)

    def _seg_write(self, seg: _Segment, off: int, data) -> int:
        """Copy one payload into a segment; returns bytes written."""
        arr = np.ascontiguousarray(data)
        dst = ctypes.addressof(ctypes.c_char.from_buffer(seg.mm, off))
        self._copy_into(dst, arr)
        return arr.nbytes

    # -- zero-copy pull (is_worker_zpull_) -----------------------------------

    @property
    def _pull_ns(self) -> str:
        # Namespaced by the cluster's scheduler port (identical across the
        # cluster's processes, unlike the pid-default PS_SHM_NS) so the
        # server derives the same name the worker allocated under.
        ns = self._pull_ns_cache
        if ns is None:
            ns = self.env.find("PS_SHM_NS") or self.env.find(
                "DMLC_PS_ROOT_PORT", "0"
            )
            self._pull_ns_cache = ns
        return ns

    def _pull_segment_name(self, worker_id: int, buf_id: int) -> str:
        return f"pslpull_{self._pull_ns}_{worker_id}_{buf_id}"

    def alloc_pull_segment(self, buf_id: int, nbytes: int):
        """Worker-side: create the registered pull buffer as a shm segment
        servers on this host write into directly (the rdma_van
        pull_addr_ / ucx w_pool_ analog).  Returns a uint8 view."""
        name = self._pull_segment_name(self.my_node.id, buf_id)
        seg = self._segment(name, nbytes, create=True)
        return np.frombuffer(seg.mm, dtype=np.uint8, count=nbytes)

    _MAX_PULL_MAPPINGS = 64

    def _cap_pull_mappings(self) -> None:
        """Bound server-side mappings of OTHER nodes' pull segments: the
        worker unlinks freed segments, but this process's cached mmap
        would keep the pages resident forever (buf_ids never repeat, so
        stale entries are never displaced).  Evict oldest beyond a
        window; a still-live segment just re-opens on next use."""
        mine = f"pslpull_{self._pull_ns}"
        with self._seg_mu:
            names = [
                n for n, s in self._segments.items()
                if n.startswith(mine) and not s.created
            ]
            for n in names[: max(0, len(names) - self._MAX_PULL_MAPPINGS)]:
                self._segments.pop(n).close()

    def free_pull_segment(self, buf_id: int) -> None:
        """Release a registered pull buffer's segment (unlink + unmap)."""
        name = self._pull_segment_name(self.my_node.id, buf_id)
        with self._seg_mu:
            seg = self._segments.pop(name, None)
        if seg is not None:
            seg.close(unlink=True)

    def _try_zpull_send(self, msg: Message) -> int:
        """Server-side: write the pull-response payload straight into the
        worker's registered segment; only keys (+lens) cross the socket.
        Returns -1 when the fast path doesn't apply."""
        m = msg.meta
        if (
            m.request
            or not m.pull
            or m.option != OPT_ZPULL
            or len(msg.data) < 2
            or not m.control.empty()
            or not self._same_host(m.recver)
        ):
            return -1
        buf_id = m.addr >> ZPULL_OFF_BITS
        off = m.addr & ((1 << ZPULL_OFF_BITS) - 1)
        name = self._pull_segment_name(m.recver, buf_id)
        vals = msg.data[1]
        arr = np.ascontiguousarray(vals.data)
        with self._seg_mu:
            is_new_mapping = name not in self._segments
        try:
            # No exists() pre-check: the worker may unlink the segment
            # between a check and the open (shutdown race) — treat any
            # open failure as "not registered" and fall back.
            seg = self._segment(name, off + arr.nbytes, create=False)
        except OSError:
            return -1
        if seg.size < off + arr.nbytes:
            return -1
        self._seg_write(seg, off, arr)
        if is_new_mapping:
            # Eviction only matters when the mapping count grew.
            self._cap_pull_mappings()

        desc = {
            "zpull_seg": name,
            "off": off,
            "nbytes": arr.nbytes,
            "code": m.data_type[1],
        }
        if m.body:
            # Preserve a user body, same invariant as the generic path.
            desc["body"] = base64.b64encode(bytes(m.body)).decode("ascii")
        meta_only = Message()
        meta_only.meta = copy.copy(m)
        meta_only.meta.body = json.dumps(desc).encode()
        meta_only.meta.shm_data = True
        meta_only.meta.data_type = (
            [m.data_type[0]] + list(m.data_type[2:])
        )
        meta_only.data = [msg.data[0]] + list(msg.data[2:])
        return super().send_msg(meta_only) + arr.nbytes

    def _native_submit(self, msg: Message):
        """The shm data plane owns payload routing (segment placement,
        zpull descriptors, ring pipes) INSIDE send_msg — the native
        sender lanes would bypass all of it, so this van always takes
        the Python path (ISSUE 6: shm van unchanged)."""
        return None

    def send_msg(self, msg: Message) -> int:
        m = msg.meta
        sent = self._try_zpull_send(msg)
        if sent >= 0:
            return sent
        total = sum(d.nbytes for d in msg.data)
        if (
            not msg.data
            or not m.control.empty()
            or total < self._min_bytes
            or not self._same_host(m.recver)
        ):
            return super().send_msg(msg)

        # Segment identity mirrors the reference's per-key shm naming
        # (rdma_utils.h:63-65); reused across iterations.  Chunked
        # transfers (docs/chunking.md) suffix the chunk INDEX: the
        # chunks of one message would otherwise collide on a single
        # segment and overwrite each other before the receiver copies
        # them out; indexing (not xfer id) keeps the names — and the
        # segments — reusable across iterations of the same key.
        ck = m.chunk
        name = (
            f"psl_{self._ns}_{m.sender}_{m.recver}_{m.key}"
            f"_{int(m.push)}{int(m.request)}"
            + (f"_c{ck.index}" if ck is not None else "")
        )
        try:
            seg = self._segment(name, total, create=True)
        except OSError as exc:
            # /dev/shm exhausted (ENOSPC) or otherwise unusable: deliver
            # over the socket instead of failing the send.
            log.warning(
                f"shm segment {name} unavailable ({exc!r}); "
                f"sending over the socket"
            )
            return super().send_msg(msg)
        off = 0
        for d in msg.data:
            off += self._seg_write(seg, off, d.data)

        meta_only = Message()
        meta_only.meta = copy.copy(m)  # don't mutate the caller's message
        # The descriptor rides in body, gated by the wire-level shm_data
        # flag (never by sniffing user bodies).
        meta_only.meta.shm_data = True
        desc = {
            "seg": name,
            "lens": [d.nbytes for d in msg.data],
            # Chunk messages carry a canonical EMPTY data_type (their
            # slices are raw uint8, code 2 — chunking.split_message);
            # pad so the receive side rebuilds every segment.
            "codes": [m.data_type[i] if i < len(m.data_type) else 2
                      for i in range(len(msg.data))],
        }
        if m.body:
            # Preserve a user body riding alongside data segments — the
            # descriptor must not destroy it (Meta.body and data are
            # independent channels in the reference's message model).
            desc["body"] = base64.b64encode(bytes(m.body)).decode("ascii")
        meta_only.meta.body = json.dumps(desc).encode()
        # Keep data_size for byte accounting but strip payload from the frame.
        sent = super().send_msg(meta_only)
        return sent + total

    def recv_msg(self):
        msg = super().recv_msg()
        if msg is None:
            return None
        if msg.meta.shm_data:
            info = json.loads(msg.meta.body.decode())
            msg.meta.shm_data = False
            if "zpull_seg" in info:
                # Worker-side zero-copy pull: the payload already sits in
                # the registered buffer (same mmap this process handed
                # out in alloc_pull_segment) — alias it back into the
                # message so the app sees delivery-in-place.
                try:
                    seg = self._segment(
                        info["zpull_seg"], info["off"] + info["nbytes"],
                        create=False,
                    )
                except OSError:
                    # Buffer freed while the response was in flight:
                    # deliver the message without vals (the waiter was
                    # abandoned along with the buffer).
                    log.warning(
                        f"zpull segment {info['zpull_seg']} gone; "
                        f"dropping payload"
                    )
                    msg.meta.body = b""
                    return msg
                vals = np.frombuffer(
                    seg.mm, dtype=wire.code_dtype(info["code"]),
                    count=info["nbytes"] // np.dtype(
                        wire.code_dtype(info["code"])
                    ).itemsize,
                    offset=info["off"],
                )
                msg.data = [msg.data[0], SArray(vals)] + list(msg.data[1:])
                msg.meta.data_type = (
                    [msg.meta.data_type[0], info["code"]]
                    + list(msg.meta.data_type[1:])
                )
                msg.meta.body = (
                    base64.b64decode(info["body"]) if "body" in info
                    else b""
                )
                return msg
            seg = self._segment(info["seg"], sum(info["lens"]), create=False)
            view = memoryview(seg.mm)
            off = 0
            msg.data = []
            msg.meta.data_type = list(info["codes"])
            for ln, code in zip(info["lens"], info["codes"]):
                arr = np.frombuffer(
                    view[off : off + ln], dtype=wire.code_dtype(code)
                )
                msg.data.append(SArray(arr))
                off += ln
            msg.meta.body = (
                base64.b64decode(info["body"]) if "body" in info else b""
            )
        return msg

    def stop_transport(self) -> None:
        super().stop_transport()
        # The copy pool is shared and process-lived: never closed here.
        with self._seg_mu:
            for seg in self._segments.values():
                seg.close(unlink=seg.created)
            self._segments.clear()
