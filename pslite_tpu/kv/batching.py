"""Small-op aggregation plane (docs/batching.md).

"RPC Considered Harmful" (PAPERS.md): for small transfers the
per-message SOFTWARE cost — one frame, one lane handoff, one customer
dispatch, one response — dominates, not the bytes.  The native plane
(PR 6) and the codec tier (PR 7) moved the bytes/s ceiling; this module
moves the ops/s ceiling by restructuring what rides the wire: one
``EXT_BATCH`` frame carries N independent small KV ops to the same
destination, the server decodes it once and fans the sub-ops into the
apply pool as a group, and ONE response frame carries every sub-op's
result (with per-op error/overload codes and per-op hot-cache stamps).

Worker side, :class:`OpCombiner` is a per-``(destination, tenant,
priority, codec)`` adaptive coalescer hanging off ``KVWorker._send``:

- Ops queue per group; a dedicated dispatch thread drains whole groups
  and sends them as one frame.  With ``PS_BATCH_WINDOW_US=0`` (the
  default) a group closes at the NEXT dispatcher pickup — an idle
  worker's op is picked up immediately (one thread wakeup, no timer
  latency), while a storm naturally accumulates ops behind the
  in-flight send, which is where the batching win lives.
- ``PS_BATCH_BYTES`` caps a frame's payload; reaching it flushes
  inline on the submitting thread (backpressure, bounded memory).
- A group of ONE op is sent as the original unbatched message —
  low-load traffic is frame-for-frame identical to an unbatched build.

The async Push/Pull/Wait contract is unchanged: every sub-op keeps its
own timestamp, callback, and deadline; retries and failovers re-slice
and re-send PER SUB-OP through the ordinary unbatched path.

Declines (documented in docs/batching.md): codec-mismatched ops never
merge (the codec is part of the group key); batching never crosses
tenant or priority; zero-copy (OPT_ZPULL) ops, ragged ``lens``
payloads, custom ``cmd`` heads, and elastic-membership clusters pass
through unbatched; chunking applies ABOVE the batch plane untouched
(a batch frame larger than ``PS_CHUNK_BYTES`` splits like any other
data message — EXT_BATCH is packed before EXT_CHUNK).  TRACED ops
MERGE like any other (their ids ride the per-op table and are echoed
on batched responses) — forcing them out of the batch plane would
make the tracer perturb exactly the path it is meant to explain
(docs/observability.md).

Capability: EXT_BATCH frames are only sent to peers that answered the
``BATCH_PROBE_CMD`` capability probe (``PS_BATCH_NEGOTIATE=0`` skips
the probe and asserts a homogeneous cluster), so decoders that predate
the extension never see a frame they cannot parse.

Response direction (docs/batching.md, "Response aggregation"): the
same :class:`OpCombiner` runs on the SERVER with ``response=True``
(``PS_RESP_BATCH_BYTES``), coalescing independent small pull results
and push acks headed back to one ``(sender, tenant, priority)`` lane —
whether the requests arrived batched or as separate frames within an
aggregation window — into one ``response_batch``-shaped EXT_BATCH
frame.  Per-op result codes and hot-cache stamps ride the per-op
table (:func:`build_batch_message` carries ``option``/``stamp``
through), and the server only ever aggregates toward senders that
proved themselves batch-aware (a capability probe or an EXT_BATCH
request received from them), so un-upgraded workers never see an
aggregated response.
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..message import BatchInfo, BatchOp, Message
from ..utils import logging as log
from ..wire import BATCH_MAX_OPS

# meta.head marker of the batch capability probe (docs/batching.md):
# a tiny pull answered BEFORE the handler — the response's vals carry
# the responder's BATCH_WIRE_VERSION.  A peer that errors (or never
# parses the cmd) is recorded incapable and only ever receives plain
# unbatched frames.  Distinct from HOT_KEYS_CMD (0x407C), MIGRATE_CMD
# (0x314D), and REPLICA_FETCH_CMD (0x5EED).
BATCH_PROBE_CMD = 0x6BA7

# Protocol generation answered by the probe; bump when the per-op
# table layout changes incompatibly.  v2: optional per-op trace id
# (flag-gated u64 after the codec block — wire._BATCH_F_TRACE); a v1
# decoder would misparse a traced table, so v1 peers read as incapable
# and keep receiving plain frames.
BATCH_WIRE_VERSION = 2

# Hard cap on ops per frame.  The u16 wire field is the formal
# ceiling; the binding bound is the kernel's UIO_MAXIOV (1024 iovecs
# per sendmsg/writev): at <= 3 data segments per op, 256 ops keeps a
# frame's iovec list comfortably under it on every transport (the
# native core already writes in 64-iovec batches; the Python sendmsg
# path also slices, but never needs to at this cap).
MAX_OPS_PER_FRAME = min(256, BATCH_MAX_OPS)


def batchable(msg: Message, response: bool = False) -> bool:
    """Structural MERGE eligibility of one already-sliced op message
    (the caller checks capability/config separately): a plain request
    with a default head, no zero-copy placement, no trace id, and a
    fixed-k segment layout — ``keys+vals`` raw (2 segments) or
    ``keys+codes+scales`` codec (3 segments).  Ragged ``lens``
    payloads carry an extra segment either way and are declined: the
    batched server intake and response tables are fixed-k contracts.

    ``response=True`` evaluates the RESPONSE-direction twin (the
    server's response combiner, docs/batching.md): same shape rules,
    but the message must be a response, and empty-data frames (push
    acks, empty pull results — the unbatched ``response()`` sends no
    segments for those either) are mergeable with ``nseg=0``."""
    m = msg.meta
    return (
        m.control.empty()
        and m.request != response
        and m.head == 0
        and m.option == 0
        and not m.shm_data
        and m.chunk is None
        and m.batch is None
        and (0 if response else 1)
        <= len(msg.data) <= (2 if m.codec is None else 3)
    )


def op_wire_cost(msg: Message, response: bool = False) -> int:
    """Bytes one op contributes to a batch frame plus the response
    bytes it will pull back — the quantity ``PS_BATCH_BYTES`` caps.
    Response-direction frames carry the result bytes themselves, so
    only the actual segment bytes count (``val_len`` echoes the
    request's byte budget and would double-charge)."""
    sent = sum(d.nbytes for d in msg.data)
    m = msg.meta
    if not response and m.pull and not m.push:
        return sent + max(0, m.val_len)  # val_len = response nbytes
    return sent


def build_batch_message(msgs: List[Message]) -> Message:
    """Merge N sliced op messages for ONE destination into a single
    EXT_BATCH frame.  The envelope inherits the group-uniform routing
    fields (recver, tenant, priority) from the members; per-op
    identity (timestamp, key, flags, codec) moves into the table."""
    log.check(len(msgs) >= 2, "a batch needs >= 2 ops")
    head = msgs[0].meta
    env = Message()
    m = env.meta
    m.app_id = head.app_id
    m.customer_id = head.customer_id
    m.request = head.request  # False on the response-direction twin
    m.head = 0  # only plain-cmd ops are batchable
    m.recver = head.recver
    m.priority = head.priority
    m.tenant = head.tenant
    m.timestamp = head.timestamp
    m.key = head.key
    ops = []
    data = env.data
    dtypes = m.data_type
    size = 0
    for sub in msgs:
        sm = sub.meta
        m.push = m.push or sm.push
        m.pull = m.pull or sm.pull
        # Splice the member's segments directly: they were built by
        # add_data already, so their dtype codes and byte counts are
        # in the member meta — re-deriving per segment would double
        # the combiner's per-op cost.
        data.extend(sub.data)
        dtypes.extend(sm.data_type)
        size += sm.data_size
        # option/stamp carry through: always 0 on the request
        # direction (batchable() filters), per-op result codes and
        # hot-cache stamps on the response direction.  The trace id
        # moves into the table — the ENVELOPE stays untraced, so span
        # recording stays per-op, never per-frame.
        ops.append(BatchOp(
            push=sm.push, pull=sm.pull, timestamp=sm.timestamp,
            key=sm.key, val_len=sm.val_len, option=sm.option,
            stamp=sm.stamp, nseg=len(sub.data), codec=sm.codec,
            trace=sm.trace,
        ))
    m.data_size = size
    m.batch = BatchInfo(ops=tuple(ops))
    return env


def split_batch_message(msg: Message) -> List[Message]:
    """Re-slice one EXT_BATCH frame into per-op messages (the inverse
    of :func:`build_batch_message`): each sub-message carries its op's
    meta fields with ``batch=None`` and exactly its ``nseg`` data
    segments.  Used for batched RESPONSES on the worker and as the
    server's conservative fallback for configurations the group apply
    declines (elastic gates, registered recv buffers)."""
    info = msg.meta.batch
    out: List[Message] = []
    di = 0
    for op in info.ops:
        sm = Message(meta=copy.copy(msg.meta))
        mm = sm.meta
        mm.batch = None
        mm.push = op.push
        mm.pull = op.pull
        mm.timestamp = op.timestamp
        mm.key = op.key
        mm.val_len = op.val_len
        mm.option = op.option
        mm.stamp = op.stamp
        mm.codec = op.codec
        mm.trace = op.trace
        mm.data_type = []
        mm.data_size = 0
        for seg in msg.data[di:di + op.nseg]:
            sm.add_data(seg)
        di += op.nseg
        out.append(sm)
    return out


class OpCombiner:
    """Per-(destination, tenant, priority, codec) op coalescer (module
    docstring).  ``send`` is the van-send callable; ``on_error(msgs,
    exc)`` fails the member ops when a flush's transport send raises
    (the combiner runs off the caller thread, so exceptions cannot
    propagate to ``push``/``pull``)."""

    def __init__(self, send: Callable[[Message], int],
                 on_error: Callable[[List[Message], Exception], None],
                 max_bytes: int, window_us: float = 0.0,
                 max_ops: int = MAX_OPS_PER_FRAME,
                 min_ops: int = 32, hold_max_us: float = 2000.0,
                 on_sent: Optional[Callable[[List[Message], Message],
                                            None]] = None,
                 response: bool = False, tracer=None):
        self._send = send
        self._on_error = on_error
        # Traced ops record their combiner dwell as a ``combine_wait``
        # span (the batch-plane analog of the van's lane_wait) — the
        # worker-queue checkpoint critical_path.py attributes from.
        if tracer is None:
            from ..telemetry.tracing import NULL_TRACER

            tracer = NULL_TRACER
        self._tracer = tracer
        # Response-direction mode (the server's response combiner,
        # docs/batching.md): eligibility and cost use the response
        # rules; everything else — lanes, order, adaptive hold — is
        # direction-agnostic.
        self._response = bool(response)
        # on_sent(members, wire_msg): the frame that actually left —
        # the worker records it per member slice so failover can
        # resender.forget() the right (possibly merged) message.
        self._on_sent = on_sent
        self.max_bytes = int(max_bytes)
        self._window_s = max(0.0, float(window_us)) / 1e6
        self._max_ops = max(2, int(max_ops))
        self._min_ops = max(2, int(min_ops))
        self._hold_max_s = max(0.0, float(hold_max_us)) / 1e6
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # group key -> [(msg, cost)]; insertion-ordered dict gives the
        # dispatcher a fair FIFO over groups.
        self._groups: Dict[Tuple, List[Tuple[Message, int]]] = {}
        self._bytes: Dict[Tuple, int] = {}
        self._first_enq: Dict[Tuple, float] = {}
        # Groups a submit_many() marked flush-ready: a whole fan-out
        # was queued atomically, so the dispatcher emits it NOW as one
        # run (one frame per lane up to the caps) — no adaptive hold.
        self._ready: set = set()
        # Adaptive hold (window 0 mode): a group that flushed within
        # _HOT_S is mid-storm — hold its next frame open _HOLD_S so the
        # producer's back-to-back ops coalesce.  A group idle longer
        # than _HOT_S never waits, so sporadic single ops dispatch at
        # the next pickup with zero timer latency.
        self._last_flush: Dict[Tuple, float] = {}
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # Counters read by tests/psmon via the worker.
        self.submitted_ops = 0
        self.flushed_frames = 0
        self.flushed_ops = 0

    @staticmethod
    def group_key(msg: Message) -> Tuple:
        """Group identity = the LANE identity: (destination, tenant,
        priority).  Everything a worker sends toward one lane flows
        through its group in submission order — including ops that can
        never MERGE (codec-mismatched, traced, oversized, zpull, custom
        cmds): those ride the stream as single frames in position, so
        an unbatchable op can never overtake queued batchable siblings
        (order-sensitive handles stay bit-exact).  Cross-group order is
        the lanes' existing cross-priority/tenant relaxation."""
        m = msg.meta
        return (m.recver, m.tenant, m.priority)

    @staticmethod
    def _merge_sig(msg: Message):
        """Frame-compatibility signature: codec-mismatched sub-ops
        never merge (docs/batching.md) — but they DO share the group's
        FIFO, emitting as separate consecutive frames.  app/customer
        ride the ENVELOPE (not the per-op table), so two customers'
        ops — possible on the response direction, where one server
        answers every app on a node — must never share a frame."""
        m = msg.meta
        ci = m.codec
        return (m.app_id, m.customer_id,
                None if ci is None else (ci.codec, ci.raw_len == 0))

    def submit(self, msg: Message) -> None:
        """Queue one sliced op for the dispatcher (the SINGLE flusher —
        per-group frame order is exactly submission order, which is
        what keeps order-sensitive handles bit-exact).  A group at the
        byte/op cap dispatches at the very next pickup; a producer that
        outruns the dispatcher far past the cap blocks briefly
        (bounded memory, natural backpressure)."""
        flush_now = None
        with self._cv:
            if self._stop:
                flush_now = [(msg, 0, False)]  # late: send inline
            else:
                import time as _time

                key, grp, nbytes = self._enqueue_locked(
                    msg, _time.monotonic())
                self._ensure_thread_locked()
                # Wake the dispatcher only when it matters — first op
                # of the group (it may be idle-waiting) or cap reached
                # (flush now); mid-hold submits would only churn its
                # timed wait.
                if (len(grp) == 1 or nbytes >= self.max_bytes
                        or len(grp) >= self._max_ops):
                    self._cv.notify_all()
                # Backpressure: far past the cap, wait for the
                # dispatcher to drain rather than balloon the queue.
                while (not self._stop
                       and self._bytes.get(key, 0) >= 4 * self.max_bytes):
                    self._cv.wait(0.05)
        if flush_now is not None:
            self._flush(flush_now)

    def submit_many(self, msgs: List[Message]) -> None:
        """Queue a whole fan-out ATOMICALLY (``KVWorker.multi_get``):
        every op lands in its lane's group under one lock acquisition,
        and each touched group is marked flush-READY — the dispatcher
        emits it at the very next pickup as one contiguous run (one
        EXT_BATCH frame per lane up to the byte/op caps), skipping the
        adaptive hold.  A serving fan-out thus costs ~one frame per
        contacted destination with no timer latency, instead of
        trickling out while the hold waits for depth."""
        if not msgs:
            return
        late: List[Message] = []
        with self._cv:
            if self._stop:
                late = list(msgs)
            else:
                import time as _time

                now = _time.monotonic()
                touched = set()
                for msg in msgs:
                    key, _grp, _nbytes = self._enqueue_locked(msg, now)
                    self._ready.add(key)
                    touched.add(key)
                self._ensure_thread_locked()
                self._cv.notify_all()
                # Same bounded-memory backpressure as submit(): a
                # producer outrunning the dispatcher blocks until its
                # touched lanes drain rather than balloon the queue.
                while (not self._stop
                       and any(self._bytes.get(k, 0) >= 4 * self.max_bytes
                               for k in touched)):
                    self._cv.wait(0.05)
        for msg in late:
            self._flush([(msg, 0, False)])

    def _enqueue_locked(self, msg: Message, now: float):
        """One op's enqueue bookkeeping (``_cv`` held) — the SINGLE
        implementation behind ``submit`` and ``submit_many``, so the
        two entry points cannot drift.  Returns ``(key, group,
        group_bytes)``."""
        key = self.group_key(msg)
        cost = op_wire_cost(msg, response=self._response)
        mergeable = (batchable(msg, response=self._response)
                     and cost <= self.max_bytes)
        if msg.meta.trace and self._tracer.active:
            msg._comb_enq = now  # combine_wait stamp, read at flush
        grp = self._groups.setdefault(key, [])
        if not grp:
            self._first_enq[key] = now
        grp.append((msg, cost, mergeable))
        self.submitted_ops += 1
        nbytes = self._bytes.get(key, 0) + cost
        self._bytes[key] = nbytes
        if not mergeable and self._response:
            # Response lanes: an unmergeable frame (above all a
            # response_batch envelope — the serving fan-in's dominant
            # return traffic) can never profit from the adaptive hold;
            # holding it would add up to hold_max_us of pure latency
            # per serving request.  Mark the lane flush-ready: the
            # dispatcher emits the whole group (in position, earlier
            # mergeable runs still merge) at the next pickup.  Request
            # lanes keep the hold — there, flushing early would cut
            # the accumulation window of mergeable siblings queued
            # behind sparse unmergeables (traced/oversized ops).
            self._ready.add(key)
        return key, grp, nbytes

    def flush_all(self) -> None:
        """Synchronously drain every queued group (stop path)."""
        while True:
            with self._cv:
                key = next(iter(self._groups), None)
                batch = self._take_locked(key) if key is not None else None
            if batch is None:
                return
            self._flush(batch)

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.flush_all()

    # -- internals -----------------------------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        t = threading.Thread(target=self._loop, name="kv-op-combiner",
                             daemon=True)
        self._thread = t
        t.start()

    def _take_locked(self, key: Tuple):
        grp = self._groups.pop(key, None)
        self._bytes.pop(key, None)
        self._first_enq.pop(key, None)
        self._ready.discard(key)
        return grp

    # Adaptive-hold parameters (window 0 mode — "close at next
    # pickup").  A group is MID-STORM when ops queued behind the
    # dispatcher's back (>= 2 at pickup) or its previous flush was
    # moments ago: its frame then stays open until it reaches
    # ``min_ops`` (or ``hold_max_us`` passes, or the byte/op cap
    # trips), so back-to-back producer ops coalesce into frames deep
    # enough to amortize the per-frame tax.  A LONE op on a cold group
    # — the low-load case — never waits: it dispatches at the very
    # next pickup, so an idle worker pays only a thread wakeup.
    _HOT_S = 500e-6
    _HOLD_TICK_S = 150e-6

    def _ready_key(self, now: float):
        """Pick a flushable group (lock held): any CAPPED group first
        (its producers may be blocked in submit's backpressure loop),
        then any cold / due group — one holding group must never
        head-of-line-block an unrelated destination's traffic.
        Returns ``(key, None)`` or ``(None, nap_s)`` with the shortest
        sleep until some group becomes due."""
        for key, grp in self._groups.items():
            if (key in self._ready
                    or self._bytes.get(key, 0) >= self.max_bytes
                    or len(grp) >= self._max_ops):
                return key, None
        nap = None
        for key, grp in self._groups.items():
            first = self._first_enq.get(key, now)
            if self._window_s > 0:
                due = first + self._window_s
            else:
                hot = (len(grp) >= 2
                       or now - self._last_flush.get(key, 0.0)
                       < self._HOT_S)
                if not hot or len(grp) >= self._min_ops:
                    return key, None
                due = first + self._hold_max_s
            if now >= due:
                return key, None
            nap = due - now if nap is None else min(nap, due - now)
        return None, nap

    def _loop(self) -> None:
        import time as _time

        while True:
            with self._cv:
                while not self._stop and not self._groups:
                    self._cv.wait()
                if self._stop:
                    return
                key, nap = self._ready_key(_time.monotonic())
                if key is None:
                    # Every group is holding: nap until the earliest
                    # deadline, tick-bounded so a group reaching
                    # min_ops mid-nap flushes within one tick.
                    self._cv.wait(min(nap, self._HOLD_TICK_S))
                    continue  # re-evaluate
                batch = self._take_locked(key)
                if batch:
                    self._last_flush[key] = _time.monotonic()
                    if len(self._last_flush) > 256:
                        self._last_flush.pop(next(iter(self._last_flush)))
                    self._cv.notify_all()  # release backpressured producers
            if batch:
                self._flush(batch)

    def _flush(self, batch: List[Tuple[Message, int, bool]]) -> None:
        """Emit one group's taken items IN ORDER as consecutive
        frames: maximal runs of merge-compatible ops (same codec
        signature, within the byte/op caps) become one EXT_BATCH
        frame; unmergeable items ride as their original single
        messages in position — the stream's order never relaxes."""
        i, n = 0, len(batch)
        while i < n:
            msg, cost, mergeable = batch[i]
            run = [msg]
            i += 1
            if mergeable:
                sig = self._merge_sig(msg)
                run_bytes = cost
                while i < n and batch[i][2] and len(run) < self._max_ops:
                    nmsg, ncost, _m = batch[i]
                    if (self._merge_sig(nmsg) != sig
                            or run_bytes + ncost > 2 * self.max_bytes):
                        break
                    run.append(nmsg)
                    run_bytes += ncost
                    i += 1
            if self._tracer.active:
                import time as _time

                now_m = _time.monotonic()
                for rm in run:
                    enq = getattr(rm, "_comb_enq", None)
                    if enq is None or not rm.meta.trace:
                        continue
                    wait_us = max(0.0, (now_m - enq) * 1e6)
                    self._tracer.span(
                        rm.meta.trace, "combine_wait",
                        self._tracer.now_us() - wait_us, wait_us,
                        args={"dst": rm.meta.recver, "run": len(run)},
                    )
            try:
                if len(run) == 1:
                    # Parity: a lone op travels as its ORIGINAL
                    # unbatched message — low-load frames are identical
                    # to an unbatched build, and single-op latency pays
                    # only the dispatcher wakeup.
                    wire_msg = run[0]
                    self._send(wire_msg)
                else:
                    self.flushed_frames += 1
                    self.flushed_ops += len(run)
                    wire_msg = build_batch_message(run)
                    self._send(wire_msg)
                if self._on_sent is not None:
                    self._on_sent(run, wire_msg)
            except Exception as exc:  # noqa: BLE001 - fail the members
                try:
                    self._on_error(run, exc)
                except Exception as hook_exc:  # noqa: BLE001
                    log.warning(
                        f"combiner error hook failed: {hook_exc!r}"
                    )
