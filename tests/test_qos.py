"""Multi-tenant QoS tier (docs/qos.md): tenant table, EXT_QOS wire
extension, weighted-fair queues, admission control / OPT_OVERLOAD, and
the worker-side hot-key pull cache with push-driven invalidation."""

import threading
import time

import numpy as np
import pytest

from pslite_tpu import wire
from pslite_tpu.kv.hot_cache import HotKeyCache
from pslite_tpu.message import ChunkInfo, Message, Meta
from pslite_tpu.sarray import SArray
from pslite_tpu.tenants import TenantTable
from pslite_tpu.utils.queues import DRAIN_LEVEL, LaneQueue, PriorityRecvQueue
from pslite_tpu.vans.chunking import split_message


# -- tenant table -------------------------------------------------------------


def test_tenant_table_parse():
    t = TenantTable("serve:8,train:1")
    assert t.enabled
    assert t.resolve("serve") == 1 and t.resolve("train") == 2
    assert t.resolve(None) == 0 and t.resolve(2) == 2
    assert t.weight(1) == 8.0 and t.weight(2) == 1.0
    assert t.name(1) == "serve" and t.name(0) == "default"
    # Bare names weight 1; "default" re-weights tenant 0.
    t2 = TenantTable("a,b:3,default:2")
    assert t2.weight(t2.resolve("a")) == 1.0
    assert t2.weight(0) == 2.0
    # Empty spec: trivial table, scheduling unchanged.
    t3 = TenantTable("")
    assert not t3.enabled and t3.resolve(None) == 0


def test_tenant_table_rejects_bad_specs():
    from pslite_tpu.utils.logging import CheckError

    with pytest.raises(CheckError):
        TenantTable("serve:8").resolve("typo")
    with pytest.raises(CheckError):
        TenantTable("serve:8,serve:1")
    with pytest.raises((CheckError, ValueError)):
        TenantTable("serve:0")
    # Dotted names would break the tenant.<name>.<kind> metric paths.
    with pytest.raises(CheckError):
        TenantTable("serve.v2:8")
    # Out-of-range / undeclared int ids fail loudly too: the u16 wire
    # field would silently alias them onto another tenant's quota.
    with pytest.raises(CheckError):
        TenantTable("serve:8").resolve(70000)
    with pytest.raises(CheckError):
        TenantTable("serve:8").resolve(5)


# -- EXT_QOS wire extension ---------------------------------------------------


def test_ext_qos_roundtrip():
    m = Meta(timestamp=9, sender=9, recver=8, request=True, push=True,
             tenant=3, stamp=12345, priority=1, trace=77)
    out = wire.unpack_meta(wire.pack_meta(m))
    assert out.tenant == 3 and out.stamp == 12345
    assert out.trace == 77 and out.priority == 1


def test_ext_qos_absent_when_zero():
    """Default traffic's frames stay byte-identical to pre-tenant
    builds — the extension packs only when tenant or stamp is set."""
    m = Meta(timestamp=1, sender=9, recver=8, request=True)
    base = wire.pack_meta(m)
    m.tenant = 1
    assert len(wire.pack_meta(m)) > len(base)
    m.tenant = 0
    assert wire.pack_meta(m) == base


def test_ext_qos_composes_with_chunk_and_codec():
    """EXT_CHUNK must stay the trailing extension (the native
    splitter's patch contract) with EXT_QOS present."""
    from pslite_tpu.message import CodecInfo

    m = Meta(timestamp=2, sender=9, recver=8, request=True, push=True,
             tenant=2, stamp=5,
             codec=CodecInfo(codec=1, raw_len=64, block=128),
             chunk=ChunkInfo(xfer=4, index=1, total=3, offset=100,
                             seg_lens=(8, 256), seg_types=(8, 10)))
    buf = wire.pack_meta(m)
    out = wire.unpack_meta(buf)
    assert out.tenant == 2 and out.stamp == 5
    assert out.codec.raw_len == 64
    assert out.chunk.offset == 100
    # Trailing bytes are exactly the chunk extension payload.
    assert buf.endswith(wire.pack_meta(m)[-wire.chunk_ext_payload_size(2):])


def test_chunk_split_carries_tenant():
    msg = Message()
    msg.meta.recver = 8
    msg.meta.tenant = 2
    msg.meta.stamp = 0
    msg.meta.priority = 0
    msg.add_data(SArray(np.arange(4, dtype=np.uint64)))
    msg.add_data(SArray(np.ones(1 << 16, np.float32)))
    chunks = split_message(msg, 1 << 14, xfer_id=1)
    assert chunks and len(chunks) > 1
    assert all(c.meta.tenant == 2 for c in chunks)


# -- weighted-fair queues -----------------------------------------------------


def test_weighted_fair_shares_within_15pct():
    """ISSUE 8 satellite: observed dequeue shares under saturation
    within 15% of configured weights (byte-weighted DRR)."""
    weights = {1: 8.0, 2: 1.0}
    q = LaneQueue(weights=weights)
    n = 360
    for i in range(n):
        q.push(0, ("serve", i), tenant=1, cost=1000)
        q.push(0, ("train", i), tenant=2, cost=1000)
    # Pop while BOTH tenants stay backlogged (the contended window).
    got = []
    for _ in range(n):
        item, dropped = q.pop(lambda: False, lambda: False)
        got.append(item[0])
        q.done()
    share = got.count("serve") / len(got)
    assert abs(share - 8.0 / 9.0) < 0.15, share


def test_weighted_fair_by_bytes_not_messages():
    """A tenant sending 4x bigger messages gets 4x fewer of them
    through per window — fairness is byte-weighted."""
    q = PriorityRecvQueue(lambda _x: 0, weights={1: 1.0, 2: 1.0})
    for i in range(200):
        q.push(("big", i), tenant=1, cost=4000)
        q.push(("small", i), tenant=2, cost=1000)
    popped = [q.try_pop()[0] for _ in range(150)]
    big, small = popped.count("big"), popped.count("small")
    # Equal weights, 4x cost ratio => ~4x count ratio.
    assert 2.5 < small / max(big, 1) < 6.0, (big, small)


def test_express_priority_jumps_tenants():
    q = PriorityRecvQueue(lambda _x: 0, weights={1: 100.0, 2: 1.0})
    for i in range(10):
        q.push(("bulk", i), tenant=1, cost=100)
    q.push(("express", 0), priority=1, tenant=2)
    assert q.try_pop()[0] == "express"


def test_drain_level_pops_last_across_tenants():
    q = PriorityRecvQueue(lambda _x: 0, weights={1: 1.0, 2: 1.0})
    q.push("sentinel", priority=DRAIN_LEVEL, tenant=0)
    q.push("a", tenant=1)
    q.push("b", tenant=2)
    out = [q.try_pop() for _ in range(3)]
    assert out[-1] == "sentinel" and set(out[:2]) == {"a", "b"}


def test_single_tenant_order_unchanged():
    """With no tenants (everything tenant 0) the pop order is the
    historical strict (-priority, seq) heap order."""
    q = PriorityRecvQueue(lambda x: x[0])
    seq = [(0, "a"), (2, "b"), (1, "c"), (2, "d"), (0, "e")]
    for item in seq:
        q.push(item)
    out = [q.try_pop()[1] for _ in range(5)]
    assert out == ["b", "d", "c", "a", "e"]


def test_fence_respected_with_tenants():
    q = PriorityRecvQueue(lambda _x: 0, weights={1: 1.0, 2: 1.0})
    q.push("fence", fence=True, tenant=1)
    q.push("later-hi", priority=10, tenant=2)
    assert q.try_pop() == "fence"
    assert q.try_pop() == "later-hi"


# -- hot-key cache unit -------------------------------------------------------


def test_hot_cache_fill_serve_invalidate():
    c = HotKeyCache(max_bytes=1 << 20, ttl_s=60.0)
    keys = np.array([1, 2], dtype=np.uint64)
    vals = np.arange(8, dtype=np.float32)
    c.fill(server=8, stamp=1, keys=keys, vals=vals)
    out = np.zeros(8, np.float32)
    assert c.serve(keys, out) and np.array_equal(out, vals)
    # Partial key set with one uncached key: miss, untouched semantics.
    assert not c.serve(np.array([1, 3], dtype=np.uint64), out)
    # A newer stamp from the same server invalidates older fills.
    c.observe(8, 2)
    assert not c.serve(keys, out)


def test_hot_cache_fill_race_guard():
    """A fill whose stamp predates a known push must not resurrect a
    stale value (the invalidation race)."""
    c = HotKeyCache(max_bytes=1 << 20, ttl_s=60.0)
    keys = np.array([5], dtype=np.uint64)
    c.observe(8, 10)  # a push with stamp 10 already completed
    c.fill(server=8, stamp=9, keys=keys, vals=np.ones(4, np.float32))
    out = np.zeros(4, np.float32)
    assert not c.serve(keys, out)
    assert len(c) == 0  # born-invalid fill was skipped entirely


def test_hot_cache_ttl_and_lru_bound():
    c = HotKeyCache(max_bytes=64, ttl_s=0.02)
    k1 = np.array([1], dtype=np.uint64)
    c.fill(8, 1, k1, np.ones(4, np.float32))  # 16 bytes
    out = np.zeros(4, np.float32)
    assert c.serve(k1, out)
    time.sleep(0.03)
    assert not c.serve(k1, out)  # TTL expired
    # LRU byte bound: filling past max_bytes evicts oldest.
    for k in range(2, 9):
        c.fill(8, 1, np.array([k], dtype=np.uint64),
               np.ones(4, np.float32))
    assert c.nbytes <= 64


def test_hot_cache_seed_restricts_admission():
    c = HotKeyCache(max_bytes=1 << 20, ttl_s=60.0)
    c.seed([7])
    keys = np.array([7, 8], dtype=np.uint64)
    c.fill(8, 1, keys, np.arange(8, dtype=np.float32))
    assert len(c) == 1  # only the seeded key admitted
    out = np.zeros(4, np.float32)
    assert c.serve(np.array([7], dtype=np.uint64), out)


# -- cluster-level: admission, overload, cache coherence ---------------------


def _cluster(n_workers, n_servers, ns, env):
    from pslite_tpu.benchmark import _loopback_cluster

    return _loopback_cluster(n_workers, n_servers, ns=ns, env_extra=env)


def test_admission_shed_fast_fail_and_bit_exact_store():
    """ISSUE 8 acceptance (admission half): a flooded tiny-limit
    server sheds with OPT_OVERLOAD — every wait() completes fast
    (OverloadError, never a hang) and the += store holds EXACTLY one
    unit per applied push."""
    from pslite_tpu.benchmark import _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker, OverloadError)

    env = {"PS_TENANTS": "serve:8,train:1",
           "PS_TENANT_QUEUE_LIMIT": "4"}
    nodes = _cluster(1, 1, "qos-admit", env)
    servers, workers = [], []
    try:
        srv = KVServer(0, postoffice=nodes[1])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=nodes[2])
        workers.append(w)
        keys = np.arange(8, dtype=np.uint64)
        vals = np.ones(8 * 1024, np.float32)
        tss = [w.push(keys, vals, tenant="train") for _ in range(64)]
        applied = shed = 0
        t0 = time.monotonic()
        for ts in tss:
            try:
                w.wait(ts)
                applied += 1
            except OverloadError:
                shed += 1
        assert time.monotonic() - t0 < 30.0  # fast-fail, no hangs
        assert applied + shed == 64
        assert shed > 0, "flood never tripped the tenant bound"
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out, tenant="train"))
        assert np.all(out == np.float32(applied))
        # Server-side telemetry recorded the sheds.
        snap = nodes[1].metrics.snapshot()
        assert snap["counters"]["qos.shed_requests"] == shed
        assert snap["counters"]["tenant.train.shed"] == shed
    finally:
        _teardown_cluster(nodes, workers, servers)


def test_overload_suppresses_callback():
    from pslite_tpu.benchmark import _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker, OverloadError)

    env = {"PS_TENANTS": "train:1", "PS_TENANT_QUEUE_LIMIT": "2"}
    nodes = _cluster(1, 1, "qos-cb", env)
    servers, workers = [], []
    try:
        srv = KVServer(0, postoffice=nodes[1])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=nodes[2])
        workers.append(w)
        keys = np.arange(4, dtype=np.uint64)
        vals = np.ones(4 * 2048, np.float32)
        fired = []
        tss = [w.push(keys, vals, tenant="train",
                      callback=lambda i=i: fired.append(i))
               for i in range(48)]
        shed_ts = []
        for i, ts in enumerate(tss):
            try:
                w.wait(ts)
            except OverloadError:
                shed_ts.append(i)
        assert shed_ts, "flood never shed"
        # No shed request's completion callback may have fired.
        assert not set(shed_ts) & set(fired)
    finally:
        _teardown_cluster(nodes, workers, servers)


def test_hot_cache_push_then_pull_never_stale():
    """ISSUE 8 satellite (cache correctness): across many racing
    push/pull rounds over the loopback cluster, a pull issued after
    its push's wait() returned NEVER serves the pre-push value from
    the cache (push-driven stamp invalidation)."""
    from pslite_tpu.benchmark import _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker)

    env = {"PS_HOT_CACHE": "1", "PS_HOT_CACHE_TTL_S": "60"}
    nodes = _cluster(1, 1, "qos-stale", env)
    servers, workers = [], []
    try:
        srv = KVServer(0, postoffice=nodes[1])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=nodes[2])
        workers.append(w)
        key = np.array([3], dtype=np.uint64)
        one = np.ones(64, np.float32)
        w.wait(w.push(key, one))
        out = np.zeros_like(one)
        # Background cache-warming puller keeps re-filling the entry
        # while pushes race it — the fill-vs-invalidate interleavings
        # under test.
        stop = threading.Event()

        def racer():
            buf = np.zeros_like(one)
            while not stop.is_set():
                w.wait(w.pull(key, buf))

        t = threading.Thread(target=racer, daemon=True)
        t.start()
        try:
            for i in range(2, 60):
                w.wait(w.push(key, one))        # store -> i * ones
                w.wait(w.pull(key, out))        # must observe it
                assert out[0] == np.float32(i), (out[0], i)
        finally:
            stop.set()
            t.join(timeout=10)
        hits = nodes[2].metrics.snapshot()["counters"].get(
            "kv.hot_cache.hits", 0)
        assert hits > 0, "cache never served (test lost its teeth)"
    finally:
        _teardown_cluster(nodes, workers, servers)


def test_hot_cache_hits_and_fetch_hot_keys():
    """Repeat pulls of a hot key answer locally; fetch_hot_keys
    returns the server's top-k and seeds the admission set."""
    from pslite_tpu.benchmark import _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker)

    env = {"PS_HOT_CACHE": "1", "PS_HOT_CACHE_TTL_S": "60"}
    nodes = _cluster(1, 1, "qos-hot", env)
    servers, workers = [], []
    try:
        srv = KVServer(0, postoffice=nodes[1])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=nodes[2])
        workers.append(w)
        keys = np.arange(16, dtype=np.uint64)
        vals = np.arange(16 * 32, dtype=np.float32)
        w.wait(w.push(keys, vals))
        hot = np.array([2], dtype=np.uint64)
        out = np.zeros(32, np.float32)
        for _ in range(20):
            w.wait(w.pull(hot, out))
        assert np.array_equal(out, vals[2 * 32:3 * 32])
        counters = nodes[2].metrics.snapshot()["counters"]
        assert counters["kv.hot_cache.hits"] >= 18
        # Hot-key introspection: key 2 dominates the server's top-k.
        got = w.fetch_hot_keys(k=4)
        assert 2 in got.tolist()
        assert w.hot_cache._hot is not None and 2 in w.hot_cache._hot
    finally:
        _teardown_cluster(nodes, workers, servers)


def test_weighted_fair_cluster_storm_shares():
    """End-to-end weighted-fair property over a live cluster: two
    same-priority bulk tenants saturating one worker->server lane
    dequeue in ~weight shares.  Measured at the APPLY layer (per-
    tenant request counters sampled mid-storm would race; instead we
    saturate, then check the lane scheduler directly above)."""
    from pslite_tpu.benchmark import _teardown_cluster
    from pslite_tpu.kv.kv_app import (KVServer, KVServerDefaultHandle,
                                      KVWorker)

    env = {"PS_TENANTS": "serve:4,train:1"}
    nodes = _cluster(1, 1, "qos-share", env)
    servers, workers = [], []
    try:
        srv = KVServer(0, postoffice=nodes[1])
        srv.set_request_handle(KVServerDefaultHandle())
        servers.append(srv)
        w = KVWorker(0, 0, postoffice=nodes[2])
        workers.append(w)
        keys = np.arange(4, dtype=np.uint64)
        vals = np.ones(4 * 4096, np.float32)
        # Interleaved equal offered load from both tenants.
        tss = []
        for _ in range(40):
            tss.append(w.push(keys, vals, tenant="serve"))
            tss.append(w.push(keys, vals, tenant="train"))
        for ts in tss:
            w.wait(ts)
        out = np.zeros_like(vals)
        w.wait(w.pull(keys, out))
        assert np.all(out == 80.0)  # both tenants' pushes all landed
        counters = nodes[1].metrics.snapshot()["counters"]
        assert counters["tenant.serve.requests"] == 40
        assert counters["tenant.train.requests"] == 40
    finally:
        _teardown_cluster(nodes, workers, servers)


def test_psmon_tenant_rollup_and_cache_column():
    """psmon renders the per-tenant rollup rows and the cache hit-rate
    column from a synthetic snapshot."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import psmon

    snap = {
        9: {"role": "worker", "metrics": {
            "uptime_s": 5.0,
            "counters": {"kv.hot_cache.hits": 80,
                         "kv.hot_cache.misses": 20},
        }},
        8: {"role": "server", "metrics": {
            "uptime_s": 5.0,
            "counters": {"tenant.serve.requests": 100,
                         "tenant.serve.shed": 0,
                         "tenant.train.requests": 50,
                         "tenant.train.shed": 10},
        }},
    }
    table = psmon.format_table(snap)
    assert "cache%" in table
    assert "80.0%" in table
    assert "per-tenant rollup" in table
    assert "train" in table and "shed=10" in table
