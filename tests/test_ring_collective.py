"""Fused ring push_pull kernel (ops/ring_collective.py) — correctness on
the virtual CPU mesh via the Pallas TPU interpreter, and parity with the
engine's XLA collective path.

The kernel is the TPU-native analog of the reference's steady-state
one-sided RDMA pipeline (rdma_transport.h:323-357): reduce-scatter hops,
server update in VMEM, all-gather hops — one kernel, full semaphore/DMA
flow control exercised by the interpreter.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pslite_tpu.ops.ring_collective import (
    ring_chunk_len,
    ring_push,
    ring_push_pull,
)
from pslite_tpu.parallel.engine import CollectiveEngine
from pslite_tpu.parallel.mesh import shard_map_compat as shard_map


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("kv",))


def _run_kernel(n, chunk, handle, dtype=np.float32, seed=0, bidir=True):
    rng = np.random.RandomState(seed)
    total = n * chunk
    grads = rng.randn(n, total).astype(dtype)
    store0 = rng.randn(total).astype(dtype)

    def body(store_l, grads_l):
        g = grads_l[0].reshape(n, chunk)
        return ring_push_pull(g, store_l, handle, "kv", n, bidir=bidir)

    f = jax.jit(
        shard_map(
            body,
            mesh=_mesh(n),
            in_specs=(P("kv"), P("kv", None)),
            out_specs=(P("kv"), P(None)),
        )
    )
    new_store, pulled = f(jnp.asarray(store0), jnp.asarray(grads))
    return grads, store0, np.asarray(new_store), np.asarray(pulled)


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("bidir", [True, False])
def test_ring_sum_matches_host(n, bidir):
    chunk = ring_chunk_len(n * 1024, n, bidir=bidir)
    grads, store0, new_store, pulled = _run_kernel(
        n, chunk, lambda s, a: s + a, bidir=bidir
    )
    want = store0 + grads.sum(0)
    np.testing.assert_allclose(new_store, want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(pulled, want, rtol=1e-5, atol=1e-5)


def test_ring_sgd_handle():
    n = 4
    chunk = ring_chunk_len(n * 1024, n)
    lr = 0.05
    grads, store0, new_store, pulled = _run_kernel(
        n, chunk, lambda s, a: s - lr * a, seed=1
    )
    want = store0 - lr * grads.sum(0)
    np.testing.assert_allclose(new_store, want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(pulled, want, rtol=1e-5, atol=1e-5)


def test_ring_bf16():
    n = 2
    chunk = ring_chunk_len(n * 2048, n, jnp.bfloat16)
    assert chunk % 2048 == 0  # (16, 128) tile for 2-byte dtypes
    rng = np.random.RandomState(2)
    total = n * chunk
    grads = rng.randn(n, total).astype(np.float32)
    store0 = rng.randn(total).astype(np.float32)

    def body(store_l, grads_l):
        g = grads_l[0].reshape(n, chunk)
        return ring_push_pull(g, store_l, lambda s, a: s + a, "kv", n)

    f = jax.jit(
        shard_map(
            body,
            mesh=_mesh(n),
            in_specs=(P("kv"), P("kv", None)),
            out_specs=(P("kv"), P(None)),
        )
    )
    new_store, pulled = f(
        jnp.asarray(store0, jnp.bfloat16), jnp.asarray(grads, jnp.bfloat16)
    )
    want = (
        store0.astype(np.float32)
        + grads.astype(np.float32).sum(0)
    )
    np.testing.assert_allclose(
        np.asarray(new_store, np.float32), want, rtol=0.05, atol=0.1
    )
    np.testing.assert_allclose(
        np.asarray(pulled, np.float32), want, rtol=0.05, atol=0.1
    )


@pytest.mark.parametrize("n", [2, 3, 4])
@pytest.mark.parametrize("bidir", [True, False])
def test_ring_push_only(n, bidir):
    chunk = ring_chunk_len(n * 1024, n, bidir=bidir)
    total = n * chunk
    rng = np.random.RandomState(7)
    grads = rng.randn(n, total).astype(np.float32)
    store0 = rng.randn(total).astype(np.float32)

    def body(store_l, grads_l):
        g = grads_l[0].reshape(n, chunk)
        return ring_push(g, store_l, lambda s, a: s + a, "kv", n,
                         bidir=bidir)

    f = jax.jit(
        shard_map(
            body,
            mesh=_mesh(n),
            in_specs=(P("kv"), P("kv", None)),
            out_specs=P("kv"),
        )
    )
    new_store = np.asarray(f(jnp.asarray(store0), jnp.asarray(grads)))
    np.testing.assert_allclose(
        new_store, store0 + grads.sum(0), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("n,bidir", [(2, False), (4, True)])
def test_ring_compressed(n, bidir):
    """int8 wire compression: quantization error bounded by the per-hop
    absmax scale; result tracks the exact sum at ~1% relative error for
    gaussian data.

    (n=8 is excluded on purpose: the TPU interpreter scheduling 8
    simulated devices on this 1-vCPU host stalls nondeterministically on
    the compressed kernel's heavier per-step op mix; the kernel is
    n-generic and the schedule identical for all n.)"""
    chunk = ring_chunk_len(n * 1024, n, bidir=bidir, compress=True)
    rng = np.random.RandomState(5)
    total = n * chunk
    grads = rng.randn(n, total).astype(np.float32)
    store0 = rng.randn(total).astype(np.float32)

    def body(store_l, grads_l):
        g = grads_l[0].reshape(n, chunk)
        return ring_push_pull(g, store_l, lambda s, a: s + a, "kv", n,
                              bidir=bidir, compress=True)

    f = jax.jit(
        shard_map(
            body,
            mesh=_mesh(n),
            in_specs=(P("kv"), P("kv", None)),
            out_specs=(P("kv"), P(None)),
        )
    )
    new_store, pulled = f(jnp.asarray(store0), jnp.asarray(grads))
    want = store0 + grads.sum(0)
    # Error bound: each RS hop re-quantizes the partial sum (scale ~
    # amax/127 each), the AG payload quantizes once.
    amax = np.abs(grads).max() * n + np.abs(store0).max()
    bound = 2 * n * amax / 127
    assert np.abs(np.asarray(new_store) - want).max() < bound
    assert np.abs(np.asarray(pulled) - want).max() < bound
    # and it is actually close, not just bounded:
    rel = np.abs(np.asarray(pulled) - want).max() / np.abs(want).max()
    assert rel < 0.05, rel


def test_engine_compressed_roundtrip():
    n = 4
    eng = CollectiveEngine(mesh=_mesh(n), impl="pallas",
                           wire_compress="int8")
    keys = np.arange(2, dtype=np.uint64)
    eng.register_dense("c", keys, 500)  # kernel pads to the int8 tile
    rng = np.random.RandomState(6)
    grads = rng.randn(n, 1000).astype(np.float32)
    out = np.asarray(eng.push_pull("c", grads))
    want = grads.sum(0)
    rel = np.abs(out - want).max() / np.abs(want).max()
    assert rel < 0.05, rel
    # push-only leg with compression, then exact pull of the lossy store
    eng.push("c", grads)
    out2 = np.asarray(eng.pull("c"))
    rel2 = np.abs(out2 - 2 * want).max() / np.abs(2 * want).max()
    assert rel2 < 0.05, rel2


def test_ring_randomized_configs():
    """Property check across random ring sizes / chunk shapes / handles:
    the fused kernel must match the host reduction bit-for-bit-ish for
    any tile-legal geometry."""
    rng = np.random.RandomState(99)
    handles = {
        "sum": lambda s, a: s + a,
        "assign": lambda s, a: a,
        "sgd": lambda s, a: s - 0.3 * a,
    }
    for trial in range(4):
        n = int(rng.choice([2, 3, 4, 8]))
        bidir = bool(rng.randint(2))
        chunk = ring_chunk_len(
            n * int(rng.randint(1, 5)) * 1024, n, bidir=bidir
        )
        name, handle = list(handles.items())[trial % len(handles)]
        grads, store0, new_store, pulled = _run_kernel(
            n, chunk, handle, seed=trial, bidir=bidir
        )
        agg = grads.sum(0)
        want = {
            "sum": store0 + agg,
            "assign": agg,
            "sgd": store0 - 0.3 * agg,
        }[name]
        np.testing.assert_allclose(
            new_store, want, rtol=1e-4, atol=1e-4,
            err_msg=f"trial={trial} n={n} bidir={bidir} handle={name}",
        )
        np.testing.assert_allclose(
            pulled, want, rtol=1e-4, atol=1e-4,
            err_msg=f"trial={trial} n={n} bidir={bidir} handle={name}",
        )


class TestEnginePallasImpl:
    """Engine integration: impl='pallas' must agree with impl='xla'."""

    def _engines(self, n, handle="sum"):
        mesh = _mesh(n)
        ex = CollectiveEngine(mesh=mesh, server_handle=handle, impl="xla")
        ep = CollectiveEngine(mesh=mesh, server_handle=handle, impl="pallas")
        return ex, ep

    def test_push_pull_parity_tile_aligned(self):
        n = 4
        ex, ep = self._engines(n)
        keys = np.arange(4, dtype=np.uint64)
        val_len = 1024 * n // 4  # total = 4096 = n*1024, tile-aligned
        rng = np.random.RandomState(3)
        grads = rng.randn(n, 4 * val_len).astype(np.float32)
        for eng in (ex, ep):
            eng.register_dense("b", keys, val_len)
        for step in range(3):
            ox = np.asarray(ex.push_pull("b", grads * (step + 1)))
            op = np.asarray(ep.push_pull("b", grads * (step + 1)))
            np.testing.assert_allclose(op, ox, rtol=1e-5, atol=1e-5)

    def test_push_pull_parity_needs_padding(self):
        # total = 8*300 = 2400 -> chunk0 = 300, kernel pads to 1024.
        n = 8
        ex, ep = self._engines(n, handle="sgd:0.1")
        keys = np.arange(8, dtype=np.uint64)
        rng = np.random.RandomState(4)
        grads = rng.randn(n, 8 * 300).astype(np.float32)
        for eng in (ex, ep):
            eng.register_dense("p", keys, 300)
        ox = np.asarray(ex.push_pull("p", grads))
        op = np.asarray(ep.push_pull("p", grads))
        np.testing.assert_allclose(op, ox, rtol=1e-5, atol=1e-5)

    def test_fallbacks_still_work(self):
        # 1-device mesh and callable handles fall back to XLA silently.
        ep = CollectiveEngine(mesh=_mesh(1), impl="pallas")
        keys = np.arange(2, dtype=np.uint64)
        ep.register_dense("f", keys, 8)
        out = np.asarray(ep.push_pull("f", np.ones(16, np.float32)))
        np.testing.assert_allclose(out, np.ones(16), rtol=1e-6)

        ep2 = CollectiveEngine(mesh=_mesh(2), impl="pallas")
        ep2.register_dense("g", keys, 1024)
        custom = lambda s, a: s + 2.0 * a  # callable -> xla path
        grads = np.ones((2, 2048), np.float32)
        out = np.asarray(ep2.push_pull("g", grads, handle=custom))
        np.testing.assert_allclose(out, 4.0 * np.ones(2048), rtol=1e-6)

    def test_push_only_parity(self):
        n = 4
        ex, ep = self._engines(n)
        keys = np.arange(4, dtype=np.uint64)
        rng = np.random.RandomState(8)
        grads = rng.randn(n, 4 * 300).astype(np.float32)
        for eng in (ex, ep):
            eng.register_dense("po", keys, 300)
            eng.push("po", grads)
            eng.push("po", grads)
        np.testing.assert_allclose(
            np.asarray(ep.pull("po")), np.asarray(ex.pull("po")),
            rtol=1e-5, atol=1e-5,
        )

    def test_group_parity(self):
        """push_pull_group on the ring impl (one dispatch, fused kernels
        back-to-back) matches the XLA group program."""
        n = 4
        ex, ep = self._engines(n, handle="sgd:0.05")
        rng = np.random.RandomState(12)
        names = ["g0", "g1", "g2"]
        lens = [256, 1024, 300]  # mixed tile-aligned and padded chunks
        grads = [
            rng.randn(n, 2 * L).astype(np.float32) for L in lens
        ]
        for eng in (ex, ep):
            for name, L in zip(names, lens):
                eng.register_dense(name, np.arange(2, dtype=np.uint64), L)
        outs_x = ex.push_pull_group(names, grads)
        outs_p = ep.push_pull_group(names, grads)
        for ox, op in zip(outs_x, outs_p):
            np.testing.assert_allclose(
                np.asarray(op), np.asarray(ox), rtol=1e-5, atol=1e-5
            )

    def test_interleaved_ops_soak(self):
        """Randomized push_pull/push/pull interleavings on the pallas
        impl track a host replay (store donation + program cache under
        op mixing)."""
        n = 8
        rng = np.random.RandomState(11)
        ep = CollectiveEngine(mesh=_mesh(n), impl="pallas")
        keys = np.arange(3, dtype=np.uint64)
        ep.register_dense("s", keys, 400)
        host = np.zeros(1200, np.float32)
        for _ in range(10):
            op = rng.choice(["push_pull", "push", "pull"])
            if op == "pull":
                np.testing.assert_allclose(
                    np.asarray(ep.pull("s")), host, rtol=1e-4, atol=1e-4
                )
                continue
            g = rng.randn(n, 1200).astype(np.float32)
            host = host + g.sum(0)
            if op == "push_pull":
                out = np.asarray(ep.push_pull("s", g))
                np.testing.assert_allclose(out, host, rtol=1e-4, atol=1e-4)
            else:
                ep.push("s", g)
        np.testing.assert_allclose(
            np.asarray(ep.pull("s")), host, rtol=1e-4, atol=1e-4
        )

    def test_pallas_then_pull_consistent(self):
        # pull (XLA program) must see the ring kernel's store update.
        n = 4
        _, ep = self._engines(n)
        keys = np.arange(4, dtype=np.uint64)
        ep.register_dense("c", keys, 1024)
        grads = np.ones((n, 4096), np.float32)
        ep.push_pull("c", grads)
        pulled = np.asarray(ep.pull("c"))
        np.testing.assert_allclose(pulled, n * np.ones(4096), rtol=1e-6)
