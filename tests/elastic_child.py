"""Child for the elastic end-to-end tests.

Worker rank 1 crashes (exit 254) after each push until it has crashed
PS_ELASTIC_CRASHES times (marker file carries the count); the launcher's
keepalive restarts it each time; the scheduler's recovery path hands it
the dead id; the final life pushes and finalizes cleanly.  Worker rank 0
polls the store until it reflects every push (rank0 once + rank1 once
per life = PS_ELASTIC_CRASHES + 2 total).
"""

import faulthandler
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

faulthandler.dump_traceback_later(180, exit=True)

import numpy as np

import pslite_tpu as ps
from pslite_tpu import KVServer, KVServerDefaultHandle, KVWorker
from pslite_tpu.message import Role


def main() -> int:
    role = os.environ["DMLC_ROLE"]
    marker = sys.argv[1]
    # PS_ELASTIC_CRASHES: how many times rank 1 crashes (the marker file
    # carries the count so each restarted life knows where it is).
    want = int(os.environ.get("PS_ELASTIC_CRASHES", "1"))
    crashes = 0
    if os.path.exists(marker):
        # Only this script writes the marker; a non-integer is a real
        # test bug and should raise loudly.
        crashes = int(open(marker).read().strip() or "0")
    if role == "worker" and crashes:
        # Recovery run: give the scheduler time to see the old id as dead.
        time.sleep(float(os.environ.get("PS_HEARTBEAT_TIMEOUT", "2")) + 1.5)
    ps.start_ps()
    server = None
    if role == "server":
        server = KVServer(0)
        server.set_request_handle(KVServerDefaultHandle())
    if role == "worker":
        po = ps.postoffice(Role.WORKER)
        worker = KVWorker(0, 0)
        keys = np.array([42], dtype=np.uint64)
        worker.wait(worker.push(keys, np.ones(8, dtype=np.float32)))
        if po.my_rank() == 1 and crashes < want:
            with open(marker, "w") as f:
                f.write(str(crashes + 1))
            os._exit(254)  # crash AFTER push, BEFORE finalize
        if po.is_recovery:
            print("RECOVERED_OK", flush=True)
        if po.my_rank() == 0:
            out = np.zeros(8, dtype=np.float32)
            deadline = time.time() + 120
            while time.time() < deadline:
                worker.wait(worker.pull(keys, out))
                if out[0] >= want + 2.0:  # rank0 once + rank1 want+1 times
                    print("POLL_OK", flush=True)
                    break
                time.sleep(0.5)
            else:
                print(f"POLL_FAIL out={out[0]}", flush=True)
                return 1
    ps.finalize()
    if server is not None:
        server.stop()
    print(f"{role} ELASTIC_DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
