"""Chunked streaming transfers (PS_CHUNK_BYTES — docs/chunking.md).

Covers the wire extension roundtrip, split/reassembly bit-exactness
(any chunk arrival order), lane interleave of priority ops between
chunks, MultiVan rail striping of one transfer, streaming apply
overlap, reassembly-state reclamation on peer death, failover of a
whole chunked slice, the chunked-vs-monolithic bit-exact storm (with
int8 compression and replication), and the recv-pool budget/size-class
satellite.
"""

import threading
import time

import numpy as np
import pytest

from pslite_tpu import wire
from pslite_tpu.environment import Environment
from pslite_tpu.message import ChunkInfo, Message, OPT_XFER_PART
from pslite_tpu.sarray import SArray
from pslite_tpu.vans.chunking import ChunkAssembler, split_message
from pslite_tpu.vans.van import Van

from helpers import LoopbackCluster


class _StubPo:
    is_scheduler = False
    is_worker = True

    def __init__(self, env):
        self.env = env

    @staticmethod
    def role_str() -> str:
        return "test"


def _wait_until(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


def _big_msg(nkeys=16, val_len=1024, sender=9, recver=8, push=True,
             seed=0, lens=False):
    msg = Message()
    m = msg.meta
    m.sender, m.recver = sender, recver
    m.request = True
    m.push = push
    m.app_id = 0
    m.timestamp = 3
    keys = np.arange(nkeys, dtype=np.uint64)
    vals = np.random.default_rng(seed).normal(
        size=nkeys * val_len).astype(np.float32)
    msg.add_data(SArray(keys))
    msg.add_data(SArray(vals))
    if lens:
        msg.add_data(SArray(np.full(nkeys, val_len, np.int32)))
    return msg, keys, vals


def _roundtrip(chunk_msg):
    """One chunk through the real wire format."""
    meta = wire.unpack_meta(wire.pack_meta(chunk_msg.meta))
    return wire.rebuild_message(
        meta, [np.asarray(d.data) for d in chunk_msg.data]
    )


# -- wire extension ----------------------------------------------------------


def test_chunk_ext_roundtrip():
    ck = ChunkInfo(xfer=123, index=7, total=9, offset=7 << 20,
                   seg_lens=(128, 1 << 20, 64), seg_types=(8, 10, 5))
    from pslite_tpu.message import Meta

    meta = Meta(app_id=1, timestamp=5, sender=9, recver=8, request=True,
                push=True, key=42, trace=0xABC, chunk=ck)
    out = wire.unpack_meta(wire.pack_meta(meta))
    assert out.chunk == ck
    assert out.trace == 0xABC  # both extensions coexist in the tail
    assert out.key == 42


def test_unchunked_meta_has_no_chunk():
    from pslite_tpu.message import Meta

    out = wire.unpack_meta(wire.pack_meta(Meta(app_id=1)))
    assert out.chunk is None


# -- split + reassembly ------------------------------------------------------


@pytest.mark.parametrize("order", ["fifo", "reversed", "shuffled"])
def test_split_reassemble_bit_exact(order):
    msg, keys, vals = _big_msg(nkeys=16, val_len=1024)
    chunks = split_message(msg, 4096, xfer_id=5)
    assert len(chunks) > 8
    assert sum(sum(d.nbytes for d in c.data)
               for c in chunks) == msg.meta.data_size
    # Canonical chunk metas (native template contract): data_type and
    # data_size stay empty/0 so every chunk of a transfer packs to the
    # same meta bytes except sid/index/offset.
    assert all(c.meta.data_size == 0 and c.meta.data_type == []
               for c in chunks)
    if order == "reversed":
        chunks = chunks[::-1]
    elif order == "shuffled":
        rng = np.random.default_rng(0)
        chunks = [chunks[i] for i in rng.permutation(len(chunks))]
    asm = ChunkAssembler()
    outs = []
    for c in chunks:
        outs.extend(asm.add(_roundtrip(c)))
    finals = [o for o in outs if o.meta.option != OPT_XFER_PART]
    parts = [o for o in outs if o.meta.option == OPT_XFER_PART]
    assert len(finals) == 1
    f = finals[0]
    assert np.array_equal(f.data[0].numpy().view(np.uint64), keys)
    assert np.array_equal(f.data[1].numpy().view(np.float32), vals)
    assert len(asm) == 0  # table empties on completion
    # Partials cover every key exactly once, in key order, bit-exact.
    covered = 0
    for p in parts:
        pk = p.data[0].numpy().view(np.uint64)
        pv = p.data[1].numpy().view(np.float32)
        assert np.array_equal(pk, keys[covered:covered + len(pk)])
        assert np.array_equal(
            pv, vals[covered * 1024:(covered + len(pk)) * 1024]
        )
        covered += len(pk)
    assert covered == len(keys)


def test_split_skips_small_and_ineligible():
    msg, _, _ = _big_msg(nkeys=2, val_len=8)
    assert split_message(msg, 1 << 20, 1) is None  # small
    big, _, _ = _big_msg(nkeys=16, val_len=1024)
    big.meta.control.cmd = wire.Command.BARRIER
    assert split_message(big, 4096, 1) is None  # control


def test_lens_payload_reassembles_but_never_streams():
    msg, keys, vals = _big_msg(nkeys=16, val_len=1024, lens=True)
    chunks = split_message(msg, 4096, xfer_id=9)
    asm = ChunkAssembler()
    outs = []
    for c in chunks:
        outs.extend(asm.add(_roundtrip(c)))
    assert all(o.meta.option != OPT_XFER_PART for o in outs)
    f = outs[-1]
    assert len(f.data) == 3
    assert np.array_equal(f.data[1].numpy().view(np.float32), vals)
    assert np.array_equal(
        f.data[2].numpy().view(np.int32), np.full(16, 1024, np.int32)
    )


def test_stale_duplicate_after_completion_is_tombstoned():
    """A retransmitted chunk landing AFTER its transfer completed (ACK
    lost, dedup signature evicted) must not re-create reassembly state
    — the partial it would emit re-applies already-applied keys."""
    msg, _, vals = _big_msg()
    chunks = split_message(msg, 8192, xfer_id=4)
    asm = ChunkAssembler()
    for c in chunks:
        asm.add(_roundtrip(c))
    assert len(asm) == 0
    assert asm.add(_roundtrip(chunks[0])) == []
    assert len(asm) == 0  # no resurrected entry


def test_corrupt_chunk_range_drops_transfer_not_process():
    """A chunk whose byte range walks past the transfer must be dropped
    with a warning — never escalate into the receive loop's fatal
    CHECK path."""
    import dataclasses

    msg, _, _ = _big_msg()
    chunks = split_message(msg, 8192, xfer_id=6)
    asm = ChunkAssembler()
    asm.add(_roundtrip(chunks[0]))
    evil = _roundtrip(chunks[1])
    evil.meta.chunk = dataclasses.replace(
        evil.meta.chunk, offset=msg.meta.data_size - 1
    )
    assert asm.add(evil) == []
    assert len(asm) == 0  # transfer dropped, process alive


def test_keys_only_push_reassembles_without_streaming():
    """A streamable-looking push with an EMPTY vals segment (keys alone
    exceed the chunk size) must reassemble fully with no partials (no
    zero-stride division)."""
    msg = Message()
    m = msg.meta
    m.sender, m.recver, m.request, m.push, m.app_id = 9, 8, True, True, 0
    keys = np.arange(4096, dtype=np.uint64)  # 32 KB of keys
    msg.add_data(SArray(keys))
    msg.add_data(SArray(np.empty(0, np.float32)))
    chunks = split_message(msg, 4096, xfer_id=8)
    asm = ChunkAssembler()
    outs = []
    for c in chunks:
        outs.extend(asm.add(_roundtrip(c)))
    assert len(outs) == 1 and outs[0].meta.option != OPT_XFER_PART
    assert np.array_equal(outs[0].data[0].numpy().view(np.uint64), keys)
    assert len(asm) == 0


def test_duplicate_chunk_ignored():
    msg, keys, vals = _big_msg()
    chunks = split_message(msg, 8192, xfer_id=2)
    asm = ChunkAssembler()
    outs = []
    for c in chunks[:-1]:
        outs.extend(asm.add(_roundtrip(c)))
        outs.extend(asm.add(_roundtrip(c)))  # duplicate: no double count
    outs.extend(asm.add(_roundtrip(chunks[-1])))
    finals = [o for o in outs if o.meta.option != OPT_XFER_PART]
    assert len(finals) == 1
    assert np.array_equal(
        finals[0].data[1].numpy().view(np.float32), vals
    )


# -- reclamation -------------------------------------------------------------


def test_assembler_reclaims_dead_peer_and_stale_transfers():
    msg, _, _ = _big_msg(sender=9)
    msg2, _, _ = _big_msg(sender=11)
    asm = ChunkAssembler(ttl_s=0.05)
    asm.add(_roundtrip(split_message(msg, 8192, 1)[0]))
    asm.add(_roundtrip(split_message(msg2, 8192, 2)[0]))
    assert len(asm) == 2
    assert asm.drop_peer(9) == 1
    assert len(asm) == 1
    time.sleep(0.1)
    asm._sweep_stale()
    assert len(asm) == 0  # TTL reclaims the abandoned transfer


def test_recovered_sender_reuses_xfer_ids_after_drop_peer():
    """drop_peer must purge COMPLETED-transfer tombstones too: a
    restarted sender's xfer counter begins at 1 again, and a stale
    tombstone would silently black-hole its first chunked pushes."""
    msg, _, vals = _big_msg(sender=9)
    chunks = split_message(msg, 8192, xfer_id=1)
    asm = ChunkAssembler()
    for c in chunks:
        asm.add(_roundtrip(c))  # completes -> tombstoned
    assert asm.add(_roundtrip(chunks[0])) == []  # dup still dropped
    asm.drop_peer(9)  # the sender restarted
    outs = []
    for c in chunks:  # new incarnation reuses xfer id 1
        outs.extend(asm.add(_roundtrip(c)))
    finals = [o for o in outs if o.meta.option != OPT_XFER_PART]
    assert len(finals) == 1
    assert np.array_equal(finals[0].data[1].numpy().view(np.float32), vals)


def test_chunking_works_with_telemetry_disabled():
    """PS_TELEMETRY=0: the chunk path's new instruments no-op (node
    snapshots stay empty) and the data plane stays correct."""
    from pslite_tpu.kv.kv_app import KVServerDefaultHandle, KVWorker

    cl = LoopbackCluster(num_workers=1, num_servers=1,
                         env_extra={"PS_CHUNK_BYTES": "8192",
                                    "PS_TELEMETRY": "0"})
    cl.start()
    servers = _mk_servers(cl, KVServerDefaultHandle)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    keys = np.array([7], dtype=np.uint64)
    vals = np.random.default_rng(2).normal(size=16384).astype(np.float32)
    w.wait(w.push(keys, vals))
    out = np.zeros_like(vals)
    w.wait(w.pull(keys, out))
    np.testing.assert_array_equal(out, vals)
    snap = cl.workers[0].telemetry_snapshot()["metrics"]
    assert not snap.get("counters")  # disabled: nothing recorded
    _teardown(cl, [w], servers)


def test_van_reclaims_partial_transfers_on_peer_death():
    van = Van(_StubPo(Environment({})))
    msg, _, _ = _big_msg(sender=9)
    chunk = _roundtrip(split_message(msg, 8192, 1)[0])
    van._assembler.add(chunk)
    assert len(van._assembler) == 1
    van.mark_peer_down(9)
    assert len(van._assembler) == 0
    van.clear_peer_down(9)
    van._assembler.add(chunk)
    van._reset_peer_sids(9)  # recovery path reclaims too
    assert len(van._assembler) == 0


# -- lane interleave ---------------------------------------------------------


def test_priority_op_interleaves_between_chunks():
    """A priority-1 message enqueued behind a chunked transfer must
    dispatch before the transfer's remaining chunks."""
    order = []
    release = threading.Event()

    class _RecordingVan(Van):
        def send_msg(self, msg):
            if not msg.meta.control.empty():
                return 0
            if msg.meta.chunk is not None:
                order.append(("chunk", msg.meta.chunk.index))
                release.wait(5)  # first chunk blocks until armed
                release.set()
            else:
                order.append(("small", msg.meta.priority))
            # Real transports return wire bytes (chunk metas carry
            # data_size 0 — the canonical template).
            return sum(d.nbytes for d in msg.data)

    van = _RecordingVan(_StubPo(Environment({"PS_CHUNK_BYTES": "4096"})))
    big, _, _ = _big_msg(nkeys=16, val_len=1024, recver=8)
    van.send(big)  # ~17 chunks into peer 8's lane
    # Chunk 0 is mid-transmit (blocked on `release`) with the rest
    # queued behind it — exactly the window a small priority op lands.
    assert _wait_until(lambda: order[:1] == [("chunk", 0)])
    small = Message()
    small.meta.sender, small.meta.recver = 9, 8
    small.meta.priority = 1
    small.add_data(SArray(np.ones(4, np.float32)))
    van.send(small)
    release.set()
    assert _wait_until(lambda: len(order) >= 18)
    van._drain_send_lanes(timeout_s=5)
    pos = order.index(("small", 1))
    assert pos == 1, order  # right after the in-flight chunk, before the rest
    # HOL accounting saw the wait behind chunk bytes.
    assert van._h_hol_wait.count >= 1
    assert van._c_chunks_sent.value >= 17


# -- live cluster ------------------------------------------------------------


def _mk_servers(cluster, handle_factory):
    from pslite_tpu.kv.kv_app import KVServer

    servers = []
    for po in cluster.servers:
        s = KVServer(0, postoffice=po)
        s.set_request_handle(handle_factory())
        servers.append(s)
    return servers


def _teardown(cluster, workers, servers):
    for w in workers:
        w.stop()
    for s in servers:
        s.stop()
    cluster.finalize()


def test_chunked_push_pull_loopback_bit_exact():
    from pslite_tpu.kv.kv_app import KVServerDefaultHandle, KVWorker

    cl = LoopbackCluster(num_workers=1, num_servers=2,
                         env_extra={"PS_CHUNK_BYTES": "8192"})
    cl.start()
    servers = _mk_servers(cl, KVServerDefaultHandle)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    span = (1 << 64) // 32
    keys = (np.arange(32, dtype=np.uint64) * span + 3).astype(np.uint64)
    vals = np.random.default_rng(7).normal(size=32 * 2048).astype(np.float32)
    w.wait(w.push(keys, vals))
    w.wait(w.push(keys, vals))
    out = np.zeros_like(vals)
    w.wait(w.pull(keys, out))
    np.testing.assert_array_equal(out, vals * 2)
    wv = cl.workers[0].van
    assert wv._c_chunks_sent.value > 0
    assert wv._c_chunks_recv.value > 0  # pull response came back chunked
    for po in cl.all_nodes():
        assert len(po.van._assembler) == 0
    for s in servers:
        assert not s._streams
    _teardown(cl, [w], servers)


def _storm(env_extra, seed=42):
    """Deterministic mixed storm; returns the final pulled state."""
    from pslite_tpu.kv.kv_app import KVServerDefaultHandle, KVWorker

    cl = LoopbackCluster(num_workers=1, num_servers=2, env_extra=env_extra)
    cl.start()
    servers = _mk_servers(cl, KVServerDefaultHandle)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    span = (1 << 64) // 8
    big_keys = (np.arange(8, dtype=np.uint64) * span + 1).astype(np.uint64)
    small_keys = (np.arange(8, dtype=np.uint64) * span + 2).astype(np.uint64)
    rng = np.random.default_rng(seed)
    big = rng.normal(size=8 * 4096).astype(np.float32)
    small = rng.normal(size=8 * 16).astype(np.float32)
    for i in range(6):
        ts1 = w.push(big_keys, big)
        ts2 = w.push(small_keys, small, priority=1)
        w.wait(ts1)
        w.wait(ts2)
        if i % 2:
            w.wait(w.push(big_keys, big, compress="int8"))
    out_b = np.zeros_like(big)
    out_s = np.zeros_like(small)
    w.wait(w.pull(big_keys, out_b))
    w.wait(w.pull(small_keys, out_s))
    for po in cl.all_nodes():
        assert len(po.van._assembler) == 0
    _teardown(cl, [w], servers)
    return out_b, out_s


@pytest.mark.parametrize("replication", [False, True])
def test_chunked_storm_matches_monolithic(replication):
    """Acceptance: the chunked storm (incl. int8 compression and, in
    one leg, PS_KV_REPLICATION=2) produces stores identical to
    PS_CHUNK_BYTES=0."""
    base = {"PS_KV_REPLICATION": "2"} if replication else {}
    chunked = _storm(dict(base, PS_CHUNK_BYTES="8192"))
    mono = _storm(dict(base, PS_CHUNK_BYTES="0"))
    np.testing.assert_array_equal(chunked[0], mono[0])
    np.testing.assert_array_equal(chunked[1], mono[1])


def test_rechunked_forward_dedup_exactly_once():
    """A worker retry of a chunked push that the primary already
    forwarded must apply exactly once — on the primary (direct dedup)
    AND on the replica (forward vs direct retry dedup)."""
    from pslite_tpu.base import server_rank_to_id
    from pslite_tpu.kv.kv_app import (
        KVPairs, KVServerDefaultHandle, KVWorker,
    )

    cl = LoopbackCluster(num_workers=1, num_servers=2,
                         env_extra={"PS_CHUNK_BYTES": "8192",
                                    "PS_KV_REPLICATION": "2"})
    cl.start()
    servers = _mk_servers(cl, KVServerDefaultHandle)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    keys = np.array([5], dtype=np.uint64)  # server rank 0's range
    vals = np.random.default_rng(3).normal(size=8192).astype(np.float32)
    w.wait(w.push(keys, vals))  # seed (chunked, forwarded)
    # Craft ONE more push and deliver it twice to the primary (a
    # resend of the same request) and once to the replica (a failover
    # retry racing the primary's forward).
    ts = w._customer.new_request(0, num_responses=3)
    part = KVPairs(keys=keys, vals=vals)
    primary = server_rank_to_id(0)
    replica = server_rank_to_id(1)
    for dest in (primary, primary, replica):
        msg = w._slice_msg(ts, True, False, 0, part, 0, dest)
        cl.workers[0].van.send(msg)
    w.wait(ts)
    by_id = {s.po.van.my_node.id: s for s in servers}
    expected = vals * 2  # seed + exactly one retry application

    def _store_val(server):
        st = server._handle.store.get(5)
        return None if st is None else st.copy()

    assert _wait_until(
        lambda: _store_val(by_id[primary]) is not None
        and np.array_equal(_store_val(by_id[primary]), expected)
    ), "primary applied the retry more than once (or not at all)"
    assert _wait_until(
        lambda: _store_val(by_id[replica]) is not None
        and np.array_equal(_store_val(by_id[replica]), expected)
    ), "replica saw the forward and the direct retry as distinct pushes"
    _teardown(cl, [w], servers)


def test_streaming_apply_overlaps_recv():
    """Partial deliveries must reach the handler BEFORE the final chunk
    arrives (apply overlaps the remaining wire time)."""
    from pslite_tpu.kv.kv_app import KVServer, KVServerDefaultHandle

    cl = LoopbackCluster(num_workers=1, num_servers=1,
                         env_extra={"PS_CHUNK_BYTES": "8192"})
    cl.start()
    server = KVServer(0, postoffice=cl.servers[0])
    handle = KVServerDefaultHandle()
    server.set_request_handle(handle)
    svan = cl.servers[0].van
    msg, keys, vals = _big_msg(nkeys=16, val_len=4096, sender=9,
                               recver=svan.my_node.id)
    msg.meta.app_id = 0
    msg.meta.customer_id = 0
    chunks = split_message(msg, 8192, xfer_id=77)
    # Deliver all but the last chunk straight into the server's intake.
    for c in chunks[:-1]:
        svan._accept_data(_roundtrip(c))
    assert _wait_until(lambda: len(handle.store) >= 8), (
        "no keys applied while the tail of the transfer is still "
        "'on the wire'"
    )
    assert len(svan._assembler) == 1
    svan._accept_data(_roundtrip(chunks[-1]))
    assert _wait_until(lambda: len(handle.store) == 16)
    assert _wait_until(lambda: not server._streams)
    assert len(svan._assembler) == 0
    for k in keys:
        np.testing.assert_array_equal(
            handle.store[int(k)],
            vals[int(k) * 4096:(int(k) + 1) * 4096],
        )
    server.stop()
    cl.finalize()


def test_server_reclaims_streams_on_worker_death():
    from pslite_tpu.kv.kv_app import KVServer, KVServerDefaultHandle

    cl = LoopbackCluster(num_workers=1, num_servers=1,
                         env_extra={"PS_CHUNK_BYTES": "8192"})
    cl.start()
    server = KVServer(0, postoffice=cl.servers[0])
    server.set_request_handle(KVServerDefaultHandle())
    svan = cl.servers[0].van
    worker_id = cl.workers[0].van.my_node.id
    msg, _, _ = _big_msg(nkeys=16, val_len=4096, sender=worker_id,
                         recver=svan.my_node.id)
    msg.meta.app_id = 0
    chunks = split_message(msg, 8192, xfer_id=9)
    for c in chunks[: len(chunks) // 2]:
        svan._accept_data(_roundtrip(c))
    assert _wait_until(lambda: len(server._streams) == 1)
    assert len(svan._assembler) == 1
    # The failure detector declares the worker dead: both the van's
    # reassembly entry and the server's open stream must reclaim.
    # (mark-then-notify is the production order, _process_node_failure.)
    svan.mark_peer_down(worker_id)
    cl.servers[0].notify_node_failure(worker_id, True)
    assert _wait_until(lambda: not server._streams)
    assert len(svan._assembler) == 0
    server.stop()
    cl.finalize()


def test_server_reclaims_stalled_streams_by_ttl():
    """A stream whose transfer died at the assembler (no final will
    ever arrive) must be reclaimed by the server's TTL sweep."""
    from pslite_tpu.kv.kv_app import KVServer, KVServerDefaultHandle

    cl = LoopbackCluster(num_workers=1, num_servers=1,
                         env_extra={"PS_CHUNK_BYTES": "8192",
                                    "PS_XFER_TIMEOUT": "0.05"})
    cl.start()
    server = KVServer(0, postoffice=cl.servers[0])
    server.set_request_handle(KVServerDefaultHandle())
    svan = cl.servers[0].van
    msg, _, _ = _big_msg(nkeys=16, val_len=4096, sender=9,
                         recver=svan.my_node.id)
    msg.meta.app_id = 0
    chunks = split_message(msg, 8192, xfer_id=13)
    for c in chunks[: len(chunks) // 2]:
        svan._accept_data(_roundtrip(c))
    assert _wait_until(lambda: len(server._streams) == 1)
    time.sleep(0.1)  # past the TTL
    server._sweep_stale_streams()
    assert not server._streams
    server.stop()
    cl.finalize()


def test_failover_rechunks_whole_slice_to_replica():
    """A chunked push to a dead rank fails over: the deadline sweeper
    re-sends the WHOLE slice (fresh transfer) to the replica and the
    wait completes; no reassembly residue anywhere."""
    from pslite_tpu.base import server_rank_to_id
    from pslite_tpu.kv.kv_app import KVServerDefaultHandle, KVWorker

    cl = LoopbackCluster(
        num_workers=1, num_servers=2,
        env_extra={
            "PS_CHUNK_BYTES": "8192",
            "PS_KV_REPLICATION": "2",
            "PS_REQUEST_TIMEOUT": "0.5",
            "PS_REQUEST_RETRIES": "4",
        },
    )
    cl.start()
    servers = _mk_servers(cl, KVServerDefaultHandle)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    keys = np.array([5], dtype=np.uint64)
    vals = np.random.default_rng(1).normal(size=16384).astype(np.float32)
    w.wait(w.push(keys, vals))  # seed while everyone is alive
    dead = server_rank_to_id(0)
    # Declare rank 0 dead at the worker (detector broadcast analog).
    cl.workers[0].van.mark_peer_down(dead)
    cl.workers[0].notify_node_failure(dead, True)
    w.wait(w.push(keys, vals))  # PeerDeadError -> sweeper -> replica
    out = np.zeros_like(vals)
    w.wait(w.pull(keys, out))  # routed to the replica too
    np.testing.assert_array_equal(out, vals * 2)
    for po in cl.all_nodes():
        assert len(po.van._assembler) == 0
    _teardown(cl, [w], servers)


def test_multivan_stripes_one_transfer_across_rails():
    from pslite_tpu.kv.kv_app import KVServerDefaultHandle, KVWorker

    cl = LoopbackCluster(
        num_workers=1, num_servers=1, van_type="multi",
        env_extra={"PS_CHUNK_BYTES": "16384", "DMLC_NUM_PORTS": "2"},
    )
    cl.start()
    servers = _mk_servers(cl, KVServerDefaultHandle)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    wvan = cl.workers[0].van
    rails_hit = set()
    orig = wvan._rail_index

    def spy(msg):
        rail = orig(msg)
        if msg.meta.chunk is not None:
            rails_hit.add(rail)
        return rail

    wvan._rail_index = spy
    keys = np.array([7], dtype=np.uint64)
    vals = np.random.default_rng(5).normal(size=128 * 1024).astype(
        np.float32)
    w.wait(w.push(keys, vals))
    out = np.zeros_like(vals)
    w.wait(w.pull(keys, out))
    np.testing.assert_array_equal(out, vals)  # reassembly bit-exact
    assert len(rails_hit) >= 2, f"chunks only observed on rails {rails_hit}"
    assert len(wvan._assembler) == 0
    _teardown(cl, [w], servers)


def test_chaos_chunked_transfers_heal():
    """Acceptance: drop/delay/dup chaos on CHUNKED transfers with
    per-chunk retransmit (PS_RESEND) + deadlines + replication: every
    wait completes and the store sums exactly; no reassembly residue
    (a dropped chunk costs one chunk's resend, not the transfer)."""
    from pslite_tpu.kv.kv_app import KVServerDefaultHandle, KVWorker

    cl = LoopbackCluster(
        num_workers=1, num_servers=2, van_type="chaos+loopback",
        env_extra={
            "PS_CHAOS": "seed=7,drop=0.08,delay=0.3:2,dup=0.05",
            "PS_RESEND": "1",
            "PS_RESEND_TIMEOUT": "60",
            "PS_CHUNK_BYTES": "4096",
            "PS_KV_REPLICATION": "2",
            "PS_REQUEST_TIMEOUT": "5",
            "PS_REQUEST_RETRIES": "4",
        },
    )
    cl.start()
    servers = _mk_servers(cl, KVServerDefaultHandle)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    keys = np.array([3, (1 << 63) + 9], dtype=np.uint64)  # both ranges
    vals = np.ones(2 * 8192, dtype=np.float32)  # ~32 KB -> 16 chunks
    rounds = 4
    for _ in range(rounds):
        w.wait(w.push(keys, vals))
    out = np.zeros_like(vals)
    w.wait(w.pull(keys, out))
    np.testing.assert_allclose(out, rounds * vals)
    injected = sum(
        sum(po.van.chaos_stats.values()) for po in cl.all_nodes()
    )
    assert injected > 0, "chaos injected nothing"
    assert cl.workers[0].van._c_chunks_sent.value > 0
    assert _wait_until(
        lambda: all(len(po.van._assembler) == 0 for po in cl.all_nodes())
    ), "reassembly state leaked across the chaos run"
    _teardown(cl, [w], servers)


def test_traced_transfer_records_xfer_span():
    """PS_TRACE_SAMPLE=1: a chunked push's trace must contain the
    per-transfer reassembly span on the server, nested under the same
    trace id as the worker's request span."""
    from pslite_tpu.kv.kv_app import KVServerDefaultHandle, KVWorker

    cl = LoopbackCluster(num_workers=1, num_servers=1,
                         env_extra={"PS_CHUNK_BYTES": "8192",
                                    "PS_TRACE_SAMPLE": "1"})
    cl.start()
    servers = _mk_servers(cl, KVServerDefaultHandle)
    w = KVWorker(0, 0, postoffice=cl.workers[0])
    keys = np.array([7], dtype=np.uint64)
    vals = np.ones(32768, np.float32)
    w.wait(w.push(keys, vals))
    tr = cl.servers[0].tracer
    with tr._mu:
        names = [e["name"] for e in tr._events]
        spans = [e for e in tr._events if e["name"] == "xfer_recv"]
    assert "xfer_recv" in names, names
    wtr = cl.workers[0].tracer
    with wtr._mu:
        req_traces = {e["args"]["trace"] for e in wtr._events
                      if e["name"] == "request"}
    assert any(s["args"]["trace"] in req_traces for s in spans)
    _teardown(cl, [w], servers)


# -- priority receive queue --------------------------------------------------


def test_priority_recv_queue_discipline():
    from pslite_tpu.utils.queues import PriorityRecvQueue

    q = PriorityRecvQueue(lambda item: item[0])
    q.push((0, "a"))
    q.push((0, "b"))
    q.push((1, "jump"))
    q.push((0, "c"))
    q.push((None, "sentinel"), priority=-(1 << 30))
    got = [q.wait_and_pop()[1] for _ in range(5)]
    assert got == ["jump", "a", "b", "c", "sentinel"]
    assert q.try_pop() is None
    assert q.wait_and_pop(timeout=0.01) is None


# -- recv pool satellite -----------------------------------------------------


def test_recv_pool_budget_and_size_classes():
    from pslite_tpu.telemetry.metrics import Registry
    from pslite_tpu.vans.tcp_van import _RecvPool

    reg = Registry()
    pool = _RecvPool(reg, budget_mb=1)
    held = [pool.acquire(64 << 10) for _ in range(4)]
    assert pool.misses == 4
    held = None  # noqa: F841 - release so the blocks go free
    b = pool.acquire(64 << 10)
    assert pool.hits == 1  # recycled a freed block
    del b
    # Size-class counters are on the registry.
    counters = reg.counters_with_prefix("tcp.recv_pool.c")
    cls = 64 << 10
    assert counters.get(f"tcp.recv_pool.c{cls}.misses") == 4
    assert counters.get(f"tcp.recv_pool.c{cls}.hits") == 1
    # Budget pressure: a bigger class evicts FREE smaller blocks
    # instead of staying permanently unpoolable.
    big = pool.acquire(768 << 10)
    del big
    big2 = pool.acquire(768 << 10)
    assert pool.hits == 2, "big class never became poolable"
    del big2


def test_recv_pool_env_budget_plumbs_through():
    from pslite_tpu.vans.tcp_van import TcpVan

    van = TcpVan(_StubPo(Environment({"PS_RECV_POOL_MB": "7",
                                      "PS_NATIVE": "0"})))
    assert van._recv_pool is not None
    assert van._recv_pool._max_total == 7 << 20
