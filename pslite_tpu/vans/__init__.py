"""Van transport family.

Equivalent of the reference's pluggable Van layer (``src/van.cc:43-104``
factory): ``tcp`` (zmq-van analog, DCN/control-plane workhorse), ``loopback``
(in-process fake for unit tests — the tier the reference fork dropped),
``ici`` (flagship TPU data plane over XLA collectives), ``shm`` (same-host
IPC fast path), ``multi`` (multi-rail composite).
"""

from __future__ import annotations


def create(van_type: str, postoffice):
    try:
        if van_type in ("tcp", "zmq", "0", ""):
            from .tcp_van import TcpVan

            return TcpVan(postoffice)
        if van_type == "loopback":
            from .loopback_van import LoopbackVan

            return LoopbackVan(postoffice)
        if van_type == "ici":
            from .ici_van import IciVan

            return IciVan(postoffice)
        if van_type in ("ici_tcp", "ici+tcp", "xla"):
            from .ici_van import IciTcpVan

            return IciTcpVan(postoffice)
        if van_type in ("ici_shm", "ici+shm"):
            from .ici_van import IciShmVan

            return IciShmVan(postoffice)
        if van_type == "shm":
            from .shm_van import ShmVan

            return ShmVan(postoffice)
        if van_type in ("multi", "multivan"):
            from .multi_van import MultiVan

            return MultiVan(postoffice)
    except ImportError as exc:
        raise ValueError(
            f"van type {van_type!r} is not available in this build: {exc}"
        ) from exc
    raise ValueError(f"unknown van type: {van_type!r}")
