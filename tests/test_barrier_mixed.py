"""Mixed-role group barriers (regression for the role-collapse deadlock).

Group (non-instance) barriers dedup senders by group rank; server id 8 and
worker id 9 both map to group rank 0, so a dedup key without role parity
makes any mixed-role barrier (SERVER_WORKER_GROUP, non-instance ALL_GROUP)
unsatisfiable — every participant hangs.  Reference behavior: the
scheduler counts barrier requests per distinct group member
(van.cc:382-426).
"""

import threading

from pslite_tpu.base import ALL_GROUP, SERVER_WORKER_GROUP

from helpers import LoopbackCluster


def _barrier_all(nodes, group):
    done = []

    def run(po):
        po.barrier(0, group, instance=False)
        done.append(po.van.my_node.id)

    threads = [
        threading.Thread(target=run, args=(po,), daemon=True) for po in nodes
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    alive = [t for t in threads if t.is_alive()]
    assert not alive, (
        f"group barrier(group={group}) deadlocked: "
        f"{len(done)}/{len(nodes)} participants returned"
    )


def test_server_worker_group_barrier():
    cluster = LoopbackCluster(num_workers=1, num_servers=1)
    cluster.start()
    try:
        _barrier_all(cluster.servers + cluster.workers, SERVER_WORKER_GROUP)
    finally:
        cluster.finalize()


def test_all_group_non_instance_barrier():
    cluster = LoopbackCluster(num_workers=2, num_servers=1)
    cluster.start()
    try:
        _barrier_all(cluster.all_nodes(), ALL_GROUP)
    finally:
        cluster.finalize()


def test_mixed_barrier_repeats():
    """Barrier state must reset between rounds for mixed groups too."""
    cluster = LoopbackCluster(num_workers=2, num_servers=2)
    cluster.start()
    try:
        for _ in range(3):
            _barrier_all(
                cluster.servers + cluster.workers, SERVER_WORKER_GROUP
            )
    finally:
        cluster.finalize()
