"""Wire-format round-trip tests (Meta pack/unpack + frames)."""

import numpy as np

from pslite_tpu import wire
from pslite_tpu.message import Command, Control, Message, Meta, Node, Role
from pslite_tpu.sarray import SArray


def _sample_meta() -> Meta:
    node_a = Node(
        role=Role.WORKER,
        id=9,
        customer_id=2,
        hostname="10.0.0.1",
        ports=[5001, 5002],
        dev_types=[2, 2],
        dev_ids=[0, 1],
        is_recovery=True,
        endpoint_name=b"\x01\x02ep",
        aux_id=3,
    )
    node_b = Node(role=Role.SERVER, id=8, hostname="10.0.0.2", ports=[6000])
    return Meta(
        head=7,
        app_id=11,
        customer_id=1,
        timestamp=42,
        sender=9,
        recver=8,
        request=True,
        push=True,
        pull=False,
        simple_app=False,
        body=b"hello-body",
        data_type=[8, 10, 5],
        control=Control(
            cmd=Command.ADD_NODE,
            node=[node_a, node_b],
            barrier_group=7,
            msg_sig=0xDEADBEEF,
        ),
        key=123456789,
        addr=0xABCDEF,
        val_len=4096,
        option=-5,
        sid=77,
        data_size=8192,
        priority=9,
        src_dev_type=2,
        src_dev_id=0,
        dst_dev_type=1,
        dst_dev_id=-1,
    )


def test_meta_roundtrip():
    meta = _sample_meta()
    buf = wire.pack_meta(meta)
    out = wire.unpack_meta(buf)
    assert out == meta


def test_empty_meta_roundtrip():
    meta = Meta()
    out = wire.unpack_meta(wire.pack_meta(meta))
    assert out == meta


def test_codec_extension_roundtrip():
    """EXT_CODEC (docs/compression.md) rides the tagged tail like
    trace/chunk: full CodecInfo round-trips, composes with the other
    extensions, and EXT_CHUNK stays the meta's TRAILING bytes (the
    native splitter patches the tail in place — a codec ext packed
    after it would be corrupted by the per-chunk patch)."""
    from pslite_tpu.message import ChunkInfo, CodecInfo

    meta = _sample_meta()
    meta.control = Control()
    meta.trace = 0x1234
    meta.codec = CodecInfo(codec=2, raw_len=1 << 26, block=128, flags=1)
    meta.chunk = ChunkInfo(xfer=5, index=1, total=3, offset=4096,
                           seg_lens=(128, 65536, 2048),
                           seg_types=(8, 2, 10))
    buf = wire.pack_meta(meta)
    out = wire.unpack_meta(buf)
    assert out.codec == meta.codec
    assert out.chunk == meta.chunk
    assert out.trace == meta.trace
    # EXT_CHUNK must be the trailing extension: its payload occupies
    # exactly the last chunk_ext_payload_size bytes of the packed meta.
    tail = wire.chunk_ext_payload_size(3)
    ck_fixed = buf[len(buf) - tail:len(buf) - tail + 8 + 4 + 4 + 8 + 1]
    import struct

    xfer, index, total, offset, nseg = struct.unpack("<QIIQB", ck_fixed)
    assert (xfer, index, total, offset, nseg) == (5, 1, 3, 4096, 3)
    # Codec alone (no chunk) round-trips too.
    meta.chunk = None
    out2 = wire.unpack_meta(wire.pack_meta(meta))
    assert out2.codec == meta.codec and out2.chunk is None


def test_frame_roundtrip():
    msg = Message(meta=Meta(app_id=3, timestamp=5, request=True, push=True))
    keys = np.array([1, 2, 3], dtype=np.uint64)
    vals = np.arange(12, dtype=np.float32)
    msg.add_data(SArray(keys))
    msg.add_data(SArray(vals))
    chunks = wire.pack_frame(msg)
    blob = b"".join(bytes(c) for c in chunks)

    meta_len, n_data = wire.unpack_frame_header(blob[: wire.FRAME_HEADER_SIZE])
    assert n_data == 2
    import struct

    off = wire.FRAME_HEADER_SIZE
    lens = struct.unpack_from("<2Q", blob, off)
    off += 16
    meta = wire.unpack_meta(blob[off : off + meta_len])
    off += meta_len
    bufs = []
    for ln in lens:
        bufs.append(blob[off : off + ln])
        off += ln
    out = wire.rebuild_message(meta, bufs)
    np.testing.assert_array_equal(out.data[0].numpy().view(np.uint64), keys)
    np.testing.assert_array_equal(out.data[1].numpy().view(np.float32), vals)
    assert out.meta.data_size == keys.nbytes + vals.nbytes


def test_pack_frame_contiguous_zero_copy():
    """Contiguous data segments pass through pack_frame without a copy
    (the chunk aliases the source buffer); strided views are made
    contiguous with identical bytes."""
    msg = Message(meta=Meta(app_id=1))
    contiguous = np.arange(16, dtype=np.float32)
    strided = np.arange(32, dtype=np.float32)[::2]
    msg.add_data(SArray(contiguous))
    msg.add_data(SArray(strided))
    chunks = wire.pack_frame(msg)
    # chunks: [hdr, lens, meta, data0, data1]
    assert np.shares_memory(np.frombuffer(chunks[3], np.float32),
                            contiguous)
    np.testing.assert_array_equal(
        np.frombuffer(chunks[4], dtype=np.float32), strided)
    assert not np.shares_memory(
        np.frombuffer(chunks[4], np.float32), strided)


def test_rebuild_message_accepts_ndarray_segments():
    """The tcp van's pooled receive path hands rebuild_message uint8
    ndarray views; derived arrays must alias them (base collapse onto
    the pool block) with correct dtypes."""
    vals = np.arange(12, dtype=np.float32)
    block = np.empty(64, np.uint8)
    block[: vals.nbytes] = vals.view(np.uint8)
    meta = Meta(data_type=[10], data_size=vals.nbytes)
    out = wire.rebuild_message(meta, [block[: vals.nbytes]])
    np.testing.assert_array_equal(out.data[0].numpy(), vals)
    assert out.data[0].numpy().base is block


def test_meta_fixed_offsets_match_native_constants():
    """The native core peeks/stamps fields of the packed meta at FIXED
    byte offsets (cpp/pslite_core.cc kMeta* constants, mirrored by
    wire.META_*_OFF).  Derive every offset from _META_FIXED's actual
    struct format so a layout reorder fails HERE instead of silently
    corrupting frames (the lane stamps sid through these offsets)."""
    import struct

    # Field order of wire._META_FIXED (see its format comment).
    fields = [
        ("version", "B"), ("head", "i"), ("app_id", "i"),
        ("customer_id", "i"), ("timestamp", "i"), ("sender", "i"),
        ("recver", "i"), ("flags", "B"), ("key", "Q"), ("addr", "Q"),
        ("val_len", "q"), ("option", "q"), ("sid", "i"),
        ("data_size", "q"), ("priority", "i"), ("src_dev_type", "b"),
        ("src_dev_id", "i"), ("dst_dev_type", "b"), ("dst_dev_id", "i"),
        ("control_cmd", "B"), ("barrier_group", "i"), ("msg_sig", "Q"),
        ("num_nodes", "H"), ("num_data_types", "H"), ("body_len", "I"),
    ]
    fmt = "<" + "".join(f for _, f in fields)
    assert struct.calcsize(fmt) == wire._META_FIXED.size, (
        "field list drifted from _META_FIXED"
    )
    off = {}
    pos = 0
    for name, f in fields:
        off[name] = pos
        pos += struct.calcsize("<" + f)
    # The constants the C++ core mirrors (kMetaSidOff & co).
    assert off["sid"] == wire.META_SID_OFF == 58
    assert off["priority"] == wire.META_PRIORITY_OFF == 70
    assert off["control_cmd"] == wire.META_CONTROL_CMD_OFF == 84
    assert wire._META_FIXED.size == wire.META_FIXED_SIZE == 105
    # Receive-side constants (sender id + variable-tail counters).
    assert off["sender"] == 17      # kMetaSenderOff
    assert off["num_nodes"] == 97   # kMetaNumNodesOff
    assert off["num_data_types"] == 99  # kMetaNumDtypesOff
    assert off["body_len"] == 101   # kMetaBodyLenOff
