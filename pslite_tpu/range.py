"""Key ranges and binary search over sorted key arrays.

Equivalent of the reference's ``include/ps/range.h:12-23`` and
``SArray::FindRange`` (``include/ps/sarray.h:344-350``).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Range:
    """Half-open interval [begin, end)."""

    begin: int
    end: int

    def size(self) -> int:
        return self.end - self.begin

    def contains(self, key: int) -> bool:
        return self.begin <= key < self.end


def find_range(sorted_keys: np.ndarray, begin: int, end: int) -> Range:
    """Index range of keys in [begin, end) within a sorted key array."""
    lo = int(np.searchsorted(sorted_keys, begin, side="left"))
    hi = int(np.searchsorted(sorted_keys, end, side="left"))
    return Range(lo, hi)
